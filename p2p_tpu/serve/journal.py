"""Crash-safe request journal: an append-only JSONL write-ahead log.

A process crash mid-trace must not lose in-flight work. The engine loop
writes one JSON line per request-state transition —

- ``admitted``   — the full request dict, at admission (before any compute)
- ``dispatched`` — the request ids of a batch, when it is handed to a runner
- ``handoff``    — a gated request crossed the phase boundary: its phase-1
  carry was spilled to a sidecar ``.npz`` (under ``<wal>.carry/``) whose
  path + pinned treedef spec ride the record — a restart resumes the
  request in phase 2 off the spill instead of re-running phase 1
- ``terminal``   — request id + final status, when the record is emitted
- ``event``      — loop-level transitions (degradation level changes)

— buffered in userspace and :meth:`Journal.sync`'d (flush + ``os.fsync``)
at batch boundaries, so the fsync cost is paid once per dispatch, not once
per line. On restart, :func:`replay` folds the log into a
:class:`ReplayState`: requests admitted but with no terminal record are the
reconstructed queue (served exactly once by the restarted loop); requests
with a terminal record are never re-run (their ids are deduped out of the
incoming trace). A torn tail — the crash happened mid-``write`` — shows up
as a truncated or garbage line: the reader *skips* it and counts it
(``skipped_corrupt``); corruption is telemetry, never a crash. Duplicate
terminal lines (a crash between the terminal append and the fsync can
replay one) collapse to the first and are counted too.

Delivery semantics: a terminal line is appended when the record is emitted
to the caller, so a crash exactly between compute and emission re-runs that
request (at-least-once compute); a crash after the terminal line treats it
as delivered (outputs are not stored in the WAL — images are the caller's
to persist). Request *state* is exactly-once; see docs/SERVING.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List

ADMITTED = "admitted"
DISPATCHED = "dispatched"
HANDOFF = "handoff"
TERMINAL = "terminal"
EVENT = "event"

#: Statuses that end a request's life; anything else in a ``terminal``
#: record is skipped as corrupt (a half-written status string).
TERMINAL_STATUSES = ("ok", "rejected", "expired", "timeout", "error",
                     "invalid_output", "cancelled", "shed")


@dataclasses.dataclass
class ReplayState:
    """What a WAL says about a previous incarnation of the loop."""

    pending: List[dict] = dataclasses.field(default_factory=list)
    terminal: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: request id -> its last ``handoff`` record (carry spill path + spec):
    #: a pending id present here resumes in phase 2 when the spill loads.
    handoffs: Dict[str, dict] = dataclasses.field(default_factory=dict)
    skipped_corrupt: int = 0
    duplicate_terminals: int = 0

    @property
    def pending_ids(self):
        return [d["request_id"] for d in self.pending]


def replay(path: str) -> ReplayState:
    """Fold the WAL at ``path`` into a :class:`ReplayState`. Missing file =
    empty state. Corrupt lines (torn tail, garbage bytes, wrong shapes) are
    skipped and counted — the reader must survive anything a crash can
    leave behind."""
    state = ReplayState()
    if not os.path.exists(path):
        return state
    admitted: Dict[str, dict] = {}
    order: List[str] = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                state.skipped_corrupt += 1
                continue
            if not isinstance(rec, dict):
                state.skipped_corrupt += 1
                continue
            kind = rec.get("type")
            if kind == ADMITTED:
                req = rec.get("request")
                rid = isinstance(req, dict) and req.get("request_id")
                if not rid:
                    state.skipped_corrupt += 1
                    continue
                if rid not in admitted:  # first admission wins
                    admitted[rid] = req
                    order.append(rid)
            elif kind == TERMINAL:
                rid = rec.get("id")
                status = rec.get("status")
                if not rid or status not in TERMINAL_STATUSES:
                    state.skipped_corrupt += 1
                    continue
                if rid in state.terminal:
                    state.duplicate_terminals += 1
                else:
                    state.terminal[rid] = status
            elif kind == HANDOFF:
                rid = rec.get("id")
                if not rid or not rec.get("carry_path"):
                    state.skipped_corrupt += 1
                    continue
                state.handoffs[rid] = rec  # last hand-off wins (retries)
            elif kind in (DISPATCHED, EVENT):
                pass  # informational; replay keys off admitted/terminal
            else:
                state.skipped_corrupt += 1
    state.pending = [admitted[rid] for rid in order
                     if rid not in state.terminal]
    return state


class Journal:
    """Append handle + the replay state of whatever the file already held.

    Opening reads the existing log first (:func:`replay`), then appends —
    one file is both the previous incarnation's evidence and the current
    one's WAL, so a chain of crashes keeps folding into one history."""

    def __init__(self, path: str):
        self.path = path
        self.replay_state = replay(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._dirty = False

    # -- writers ----------------------------------------------------------
    def _append(self, rec: dict) -> None:
        self._f.write(json.dumps(rec) + "\n")
        self._dirty = True

    def admitted(self, request_dict: dict, vnow: float) -> None:
        self._append({"type": ADMITTED, "request": request_dict,
                      "vnow_ms": round(vnow, 3)})

    def dispatched(self, request_ids, batch_index: int, vnow: float,
                   phase: int = 0) -> None:
        rec = {"type": DISPATCHED, "ids": list(request_ids),
               "batch": batch_index, "vnow_ms": round(vnow, 3)}
        if phase:
            rec["phase"] = phase
        self._append(rec)

    def handoff(self, request_id: str, vnow: float, carry_path: str,
                spec: str, trace: dict = None) -> None:
        """One gated request crossed the phase boundary; its carry spill at
        ``carry_path`` (already durably written) matches ``spec``.
        ``trace`` is the request's flight-trace context (``obs.flight``):
        it rides the WAL so a crash-replayed request resumed in phase 2 by
        a different process can stitch its timeline to the pre-crash
        phase-1 segments (absent when flight tracing is off — the record
        stays byte-identical to the pre-tracing schema)."""
        rec = {"type": HANDOFF, "id": request_id,
               "carry_path": carry_path, "spec": spec,
               "vnow_ms": round(vnow, 3)}
        if trace is not None:
            rec["trace"] = trace
        self._append(rec)

    def carry_path(self, request_id: str) -> str:
        """Where this WAL spills a request's hand-off carry: a sidecar dir
        next to the log, one ``.npz`` per request id."""
        import hashlib

        # Request ids are caller-chosen free text: hash them into the
        # filename so a hostile/awkward id ("../x", 300 chars) cannot
        # escape or break the sidecar dir; the id itself stays in the WAL.
        digest = hashlib.sha256(request_id.encode()).hexdigest()[:24]
        return os.path.join(self.path + ".carry", digest + ".npz")

    def discard_carry(self, request_id: str) -> None:
        """Drop a terminal request's spill (hygiene; best-effort)."""
        try:
            os.remove(self.carry_path(request_id))
        except OSError:
            pass

    def terminal(self, request_id: str, status: str, vnow: float) -> None:
        self._append({"type": TERMINAL, "id": request_id, "status": status,
                      "vnow_ms": round(vnow, 3)})

    def event(self, kind: str, **fields) -> None:
        self._append({"type": EVENT, "kind": kind, **fields})

    def sync(self) -> None:
        """Flush + fsync — called at batch boundaries, not per line."""
        if not self._dirty:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = False

    def close(self) -> None:
        try:
            self.sync()
        finally:
            self._f.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
