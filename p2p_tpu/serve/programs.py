"""LRU compiled-program cache for the serve loop.

A *program* here is a host-side runner bound to one ``(compile_key,
bucket)`` pair: a closure over the pipeline and every static sweep argument
(steps, scheduler, gate step, lane count). Building one warms it on
zero-valued inputs of the real batch's shapes — the XLA trace+compile (and
one cheap throwaway execution) happen at build time, so by the time real
lanes run the program, request latency is steady-state. The warm cost is
what the per-request ``compile_ms`` field reports.

The LRU evicts host handles only; the actual XLA executables additionally
live in the repo-wide persistent compile cache
(``utils.cache.default_cache_dir()``, enabled once per process via
``utils.cache.ensure_persistent_cache``), so re-building an evicted program
— or the same program in the next server process — is mostly disk I/O, not
a recompile. Counters (hits / misses / evictions) feed the per-request
records and the bench ``serve`` block.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Tuple

from ..obs import metrics as obs_metrics
from ..utils.cache import ensure_persistent_cache


class ProgramCache:
    """LRU over built runners, keyed by ``(compile_key, bucket)``.

    ``retry_policy`` (a ``serve.faults.RetryPolicy``) wraps the build
    closure on miss: a *transient* build failure (device busy mid-compile,
    RESOURCE_EXHAUSTED) backs off on the wall clock and re-tries; poison/
    fatal failures propagate immediately. The serve engine passes its own
    policy here so prewarm and in-band compile misses share it — execution
    faults are still classified at dispatch and back off on the engine's
    *virtual* clock instead.

    :meth:`quarantine` handles the watchdog path: a program whose execution
    timed out is evicted and counted — the hang may have been the device,
    not the program, so a later miss is allowed to rebuild it, but never to
    reuse the possibly-wedged handle."""

    def __init__(self, capacity: int = 8, retry_policy=None):
        if capacity < 1:
            raise ValueError(f"program cache capacity must be >= 1, "
                             f"got {capacity}")
        ensure_persistent_cache()
        self.capacity = capacity
        self.retry_policy = retry_policy
        self._lru: "OrderedDict[Tuple, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.quarantined = 0
        self.build_retries = 0
        # Mirror of the instance counters in the process registry, so the
        # Prometheus snapshot carries cache behaviour without reaching into
        # the cache object (instance counters stay the record/bench source).
        self._m_events = obs_metrics.registry().counter(
            "serve_program_cache_events_total",
            "program-cache lookups and evictions by event",
            labels=("event",))

    def __len__(self) -> int:
        return len(self._lru)

    def __contains__(self, key: Tuple) -> bool:
        return key in self._lru

    def get(self, key: Tuple, build: Callable[[], object]):
        """Return ``(runner, hit, build_ms)``; builds (and warms) on miss."""
        if key in self._lru:
            self._lru.move_to_end(key)
            self.hits += 1
            self._m_events.labels(event="hit").inc()
            return self._lru[key], True, 0.0
        self.misses += 1
        self._m_events.labels(event="miss").inc()
        t0 = time.perf_counter()
        if self.retry_policy is not None:
            from .faults import retry_call

            def _count_retry(attempt, delay_ms, exc):
                self.build_retries += 1
                self._m_events.labels(event="build_retry").inc()

            runner = retry_call(build, policy=self.retry_policy,
                                key=f"build:{key}", on_retry=_count_retry)
        else:
            runner = build()
        build_ms = (time.perf_counter() - t0) * 1000.0
        # Per-miss build/warm wall time into compile_ms{what="program"} —
        # the "where did this window's compile time go" decomposition.
        from ..obs import device as obs_device

        obs_device.record_compile(build_ms, what="program")
        self._lru[key] = runner
        while len(self._lru) > self.capacity:
            self._lru.popitem(last=False)
            self.evictions += 1
            self._m_events.labels(event="evict").inc()
        return runner, False, build_ms

    def quarantine(self, key: Tuple) -> bool:
        """Drop a suspect program (its execution timed out). Returns whether
        the key was held. Quarantine ≠ eviction in the stats: an eviction is
        capacity pressure, a quarantine is a health verdict."""
        held = self._lru.pop(key, None) is not None
        if held:
            self.quarantined += 1
            self._m_events.labels(event="quarantine").inc()
        return held

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "size": len(self._lru),
                "quarantined": self.quarantined,
                "build_retries": self.build_retries,
                "hit_rate": (self.hits / total) if total else 0.0}


def _reuse_from_key(sched_key: Tuple):
    """The reuse-schedule table from its compile-key component (or None):
    the runners rebuild the static table from the key alone, so identical
    tables from different request files build — and pool as — one
    program."""
    if sched_key is None:
        return None
    from ..engine.reuse import ReuseSchedule

    return ReuseSchedule.from_key(sched_key)


def _reuse_kwargs(gate_step, sched) -> dict:
    """The gate/schedule pair a runner's program was keyed for — mutually
    exclusive by construction (``resolve_reuse``), so exactly one is
    non-None. Shared by every runner class so the dispatch can never
    diverge between the monolithic and pool paths."""
    if sched is not None:
        return {"gate": None, "schedule": sched}
    return {"gate": gate_step, "schedule": None}


class SweepRunner:
    """Default runner: encode + stack + pad one batch, run ``parallel.sweep``.

    Encoding uses exactly the calls (and call shapes) ``text2image`` uses
    per request — cond and uncond encoded per request at the request's own
    prompt-batch size, latents drawn as ``normal(PRNGKey(seed))`` — so a
    lane's output is bitwise-identical to the direct path's for the same
    request (the quality-gate ``serve_parity`` contract).

    ``validate=True`` additionally reduces the final latents to one finite
    flag per lane (``engine.sampler.lane_finite`` — a separate tiny jitted
    program on the sweep's *output*, so the sweep program itself is
    untouched) and exposes it as ``last_lane_finite``; the engine converts
    non-finite lanes into ``invalid_output`` records instead of shipping
    the black images a NaN latent decodes to.
    """

    def __init__(self, pipe, compile_key: Tuple, bucket: int,
                 progress: bool = False, validate: bool = False,
                 heartbeat: bool = False, mesh=None, semcache=None):
        self.pipe = pipe
        (_, self.steps, self.scheduler, self.gate_step, self.group_batch,
         _, sched_key) = compile_key
        self.sched = _reuse_from_key(sched_key)
        self.bucket = bucket
        self.progress = progress
        self.validate = validate
        # ISSUE 13: the semantic cache's L1 layer — cond/uncond embeddings
        # are pure functions of (model, prompts), so repeated prompts skip
        # the text encoder. semcache=None (default) encodes every lane
        # exactly as before; a cached value is the same device array the
        # encoder produced, so reuse is bitwise by construction.
        self.semcache = semcache
        # A live jax.sharding.Mesh (or None): the sweep shards the lane
        # axis over its dp axis. Inputs are still assembled on the default
        # device; the sweep entry points stage them onto the mesh with
        # explicit NamedShardings (transfer-guard-clean either way).
        self.mesh = mesh
        # heartbeat=True traces the step callback in even when progress is
        # off (sweep's metrics flag: report=False, so nothing prints) —
        # the watchdog's liveness source must not depend on the operator
        # wanting progress lines (`--quiet --watchdog-ms` would otherwise
        # shoot every slow-but-alive in-band compile).
        self.heartbeat = heartbeat
        self.last_lane_finite = None

    def _inputs(self, entries, zeros: bool = False):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..engine.sampler import encode_prompts, init_latent, stage_host

        def encode(prompts):
            if self.semcache is None:
                return encode_prompts(self.pipe, list(prompts))
            return self.semcache.l1_get_or_build(
                (self.pipe.config.name,) + tuple(prompts),
                lambda: encode_prompts(self.pipe, list(prompts)))

        ctxs, lats, ctrls = [], [], []
        for e in entries:
            req = e.request
            cond = encode(req.prompts)
            uncond = encode(tuple([req.negative_prompt or ""]
                                  * len(req.prompts)))
            ctxs.append(jnp.concatenate([uncond, cond], axis=0))
            # The seed is staged explicitly (np.int32 is exactly what
            # PRNGKey(int) resolves to under x64-off, so keys — and lanes —
            # stay bitwise-identical): PRNGKey(python_int) is an implicit
            # h2d transfer per lane, disallowed under the dispatch
            # transfer guard. Seeds outside int32 range keep the python-int
            # path — PRNGKey folds 64-bit ints natively, while np.int32
            # would raise (and an x64-off device stage would truncate).
            seed = (stage_host(np.int32(req.seed))
                    if -2**31 <= req.seed < 2**31 else req.seed)
            _, lat_b = init_latent(None, self.pipe.latent_shape,
                                   jax.random.PRNGKey(seed),
                                   len(req.prompts))
            lats.append(lat_b)
            ctrls.append(e.prepared.controller)
        while len(ctxs) < self.bucket:  # padding lanes replicate the last
            ctxs.append(ctxs[-1])
            lats.append(lats[-1])
            ctrls.append(ctrls[-1])
        ctx = jnp.stack(ctxs)
        lat = jnp.stack(lats)
        ctrl = (None if ctrls[0] is None else
                jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *ctrls))
        if zeros:
            ctx, lat = jnp.zeros_like(ctx), jnp.zeros_like(lat)
        return ctx, lat, ctrl

    def warm(self, entries) -> None:
        """Compile-ahead: run once on zero inputs of the batch's shapes.
        Shapes (not values) determine the program, so the real batch then
        executes warm — compile stays off the request path."""
        import jax

        ctx, lat, ctrl = self._inputs(entries, zeros=True)
        imgs, _ = self._run(ctx, lat, ctrl, guidance=1.0)
        jax.device_get(imgs)

    def cost_lowered(self, entries):
        """The cost observatory's build-time hook (``obs.costmodel``): the
        ``jax.stages.Lowered`` of this runner's exact program, built off
        the same zero inputs ``warm`` compiles with. ``.compile()`` on it
        yields the XLA cost/memory analysis for the program's cost card
        (lowered mesh-less — the card describes the logical computation;
        the scope scales peaks by device count)."""
        from ..parallel import sweep

        ctx, lat, ctrl = self._inputs(entries, zeros=True)
        return sweep(self.pipe, ctx, lat, ctrl, num_steps=self.steps,
                     guidance_scale=1.0, scheduler=self.scheduler,
                     mesh=None, **self._reuse_kw(),
                     progress=self.progress, metrics=self.heartbeat,
                     lower_only=True)

    def _reuse_kw(self) -> dict:
        return _reuse_kwargs(self.gate_step, self.sched)

    def _run(self, ctx, lat, ctrl, guidance: float):
        from ..parallel import sweep

        imgs, lats = sweep(self.pipe, ctx, lat, ctrl, num_steps=self.steps,
                           guidance_scale=guidance, scheduler=self.scheduler,
                           mesh=self.mesh, **self._reuse_kw(),
                           progress=self.progress, metrics=self.heartbeat)
        return imgs, lats

    def __call__(self, entries, guidance: float):
        # d2h via jax.device_get (never np.asarray): the whole call runs
        # transfer-guard-clean — every h2d is explicitly staged upstream
        # (tokens, schedule tables, guidance), and the two d2h fetches here
        # are the only host landings. tests/test_serve.py executes a steady-
        # state batch under jax.transfer_guard("disallow") to pin it.
        import jax

        ctx, lat, ctrl = self._inputs(entries)
        imgs, lats = self._run(ctx, lat, ctrl, guidance)
        if self.validate:
            from ..engine.sampler import lane_finite

            # Fetched eagerly so the engine's per-lane bool() check reads
            # host memory, not an implicit per-lane device sync.
            self.last_lane_finite = jax.device_get(lane_finite(lats))
        return jax.device_get(imgs)


_COND_HALF_JIT = None


def _cond_half(ctx, group_batch: int):
    """``ctx[:, group_batch:]`` as a compiled program with a static start
    index — transfer-free at execution, unlike the eager slice (whose
    ``dynamic_slice`` impl stages the start index h2d per call). One
    module-level jit wrapper so the program caches per (shape, start)."""
    global _COND_HALF_JIT
    if _COND_HALF_JIT is None:
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("b",))
        def cut(x, b):
            return x[:, b:]

        _COND_HALF_JIT = cut
    return _COND_HALF_JIT(ctx, b=group_batch)


class Phase1Runner(SweepRunner):
    """Phase-1 POOL runner: the same inputs as a monolithic sweep (CFG
    context halves, shared-seed latents, full controller), but the program
    runs only steps ``[0, gate)`` and returns the per-group
    :class:`~p2p_tpu.engine.sampler.PhaseCarry` (leaves with a leading
    ``bucket`` axis) instead of images — the hand-off units the engine
    splits per lane and feeds to the separately scheduled phase-2 pool."""

    def __init__(self, pipe, compile_key: Tuple, bucket: int,
                 progress: bool = False, validate: bool = False,
                 heartbeat: bool = False, mesh=None, semcache=None):
        # Strip the "phase1" pool tag; the rest is the monolithic key
        # layout SweepRunner already parses.
        super().__init__(pipe, compile_key[1:], bucket, progress=progress,
                         validate=validate, heartbeat=heartbeat, mesh=mesh,
                         semcache=semcache)

    def _run(self, ctx, lat, ctrl, guidance: float):
        from ..parallel.sweep import sweep_phase1

        return sweep_phase1(self.pipe, ctx, lat, ctrl, num_steps=self.steps,
                            guidance_scale=guidance,
                            scheduler=self.scheduler, mesh=self.mesh,
                            **self._reuse_kw(),
                            progress=self.progress, metrics=self.heartbeat)

    def cost_lowered(self, entries):
        from ..parallel.sweep import sweep_phase1

        ctx, lat, ctrl = self._inputs(entries, zeros=True)
        return sweep_phase1(self.pipe, ctx, lat, ctrl,
                            num_steps=self.steps, guidance_scale=1.0,
                            scheduler=self.scheduler, mesh=None,
                            **self._reuse_kw(), progress=self.progress,
                            metrics=self.heartbeat, lower_only=True)

    def warm(self, entries) -> None:
        import jax

        ctx, lat, ctrl = self._inputs(entries, zeros=True)
        jax.block_until_ready(self._run(ctx, lat, ctrl, guidance=1.0))

    def __call__(self, entries, guidance: float):
        import jax

        ctx, lat, ctrl = self._inputs(entries)
        carry = self._run(ctx, lat, ctrl, guidance)
        # The hand-off unit pairs the sampler carry with the already-
        # encoded cond context half, so phase 2 never re-runs the text
        # encoder for work phase 1 already did (and a journal-resumed
        # lane needs no encoder at all). Everything STAYS on device (only
        # a journal spill fetches it to host) — but the dispatch is
        # synchronized so run_ms measures execution, not async enqueue.
        # The cond half is cut by a jitted slice with a STATIC start: an
        # eager `ctx[:, b:]` stages its start index host→device on every
        # dispatch (dynamic_slice's eager impl), which the mesh
        # transfer-guard test caught in this previously-unguarded pool.
        return jax.block_until_ready(
            {"carry": carry, "ctx": _cond_half(ctx, self.group_batch)})


class Phase2Runner:
    """Phase-2 POOL runner: packs hand-off carries from *different*
    requests (different phase-1 batches, even different edit modes — the
    phase-2 compile key reduces the controller to what survives the gate)
    into one wide single-branch batch: steps ``[gate, S)`` off each lane's
    ``AttnCache`` + residual, then the VAE decode.

    Every lane's carry is validated against the request's pinned treedef
    spec (``engine.sampler.carry_spec`` vs :func:`handoff.carry_template`)
    before it touches the compiled program — a mismatched hand-off is a
    hard error at dispatch, not an XLA shape failure three layers down."""

    def __init__(self, pipe, compile_key: Tuple, bucket: int,
                 progress: bool = False, validate: bool = False,
                 heartbeat: bool = False, mesh=None, semcache=None):
        # semcache accepted for factory uniformity; phase 2 never encodes
        # (the hand-off unit already carries the cond context).
        self.pipe = pipe
        (_, _, self.steps, self.scheduler, self.gate_step, self.group_batch,
         _, sched_key) = compile_key
        # The phase-2 PROJECTION of the reuse table (phase2_view rode the
        # key): schedules differing only before the boundary share this
        # key — and therefore this program.
        self.sched = _reuse_from_key(sched_key)
        self.bucket = bucket
        self.progress = progress
        self.validate = validate
        self.heartbeat = heartbeat
        self.mesh = mesh
        self.last_lane_finite = None
        self._expected_spec = None

    def _spec_for(self, prep) -> str:
        import jax

        from ..engine.sampler import carry_spec

        from .handoff import carry_template

        if self._expected_spec is None:
            # Abstract evaluation only: the spec is a shape/dtype/treedef
            # string, so materializing the template's zero arrays here
            # would be pure waste — and its scalar constants would be
            # *implicit* h2d transfers inside the guarded dispatch path
            # (caught by the mesh transfer-guard test; carry_spec reads
            # shapes/dtypes identically off ShapeDtypeStructs).
            self._expected_spec = carry_spec(jax.eval_shape(
                lambda: carry_template(self.pipe, prep)))
        return self._expected_spec

    def _inputs(self, entries, zeros: bool = False):
        import jax
        import jax.numpy as jnp

        from ..engine.sampler import carry_spec, phase2_controller

        from .handoff import stack_carries

        carries, ctrls = [], []
        for e in entries:
            want = self._spec_for(e.prepared)
            got = carry_spec(e.carry)
            if got != want:
                raise ValueError(
                    f"hand-off carry for request {e.request_id!r} does not "
                    f"match its pinned treedef spec:\n  got  {got}\n"
                    f"  want {want}")
            carries.append(e.carry)
            ctrls.append(phase2_controller(e.prepared.controller))
        # Pack the hand-off units (sampler carry + encoded cond context)
        # into one phase-2 batch; padding replicates the last real lane.
        # On a mesh the lanes may live on different shards: stack_carries
        # reconciles them device-to-device (no host round-trip).
        packed = stack_carries(carries, self.bucket, mesh=self.mesh)
        ctx, carry = packed["ctx"], packed["carry"]
        while len(ctrls) < self.bucket:
            ctrls.append(ctrls[-1])
        ctrl = (None if ctrls[0] is None else
                jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *ctrls))
        if zeros:
            ctx = jnp.zeros_like(ctx)
            carry = jax.tree_util.tree_map(jnp.zeros_like, carry)
        return ctx, carry, ctrl

    def _reuse_kw(self) -> dict:
        return _reuse_kwargs(self.gate_step, self.sched)

    def _run(self, ctx, carry, ctrl, guidance: float):
        from ..parallel.sweep import sweep_phase2

        return sweep_phase2(self.pipe, ctx, carry, ctrl,
                            num_steps=self.steps, guidance_scale=guidance,
                            scheduler=self.scheduler, mesh=self.mesh,
                            **self._reuse_kw(),
                            progress=self.progress, metrics=self.heartbeat)

    def _template_inputs(self, entries):
        """Zero inputs shaped by the request alone
        (``handoff.carry_template``) — shared by :meth:`warm` (which must
        prewarm before any phase-1 batch has produced a real carry) and
        :meth:`cost_lowered` (whose card must describe that same
        program)."""
        import jax
        import jax.numpy as jnp

        from ..engine.sampler import phase2_controller

        from .handoff import carry_template

        prep = entries[0].prepared
        template = carry_template(self.pipe, prep)
        lead = jax.tree_util.tree_map(
            lambda x: jnp.zeros((self.bucket,) + tuple(x.shape), x.dtype),
            template)
        ctrl = phase2_controller(prep.controller)
        ctrl_g = (None if ctrl is None else jax.tree_util.tree_map(
            lambda x: jnp.stack([x] * self.bucket), ctrl))
        return lead["ctx"], lead["carry"], ctrl_g

    def warm(self, entries) -> None:
        """Compile-ahead off zero inputs shaped by the request alone
        (``handoff.carry_template``), so the phase-2 program can prewarm
        before any phase-1 batch has produced a real carry."""
        import jax

        ctx, carry, ctrl_g = self._template_inputs(entries)
        imgs, _ = self._run(ctx, carry, ctrl_g, guidance=1.0)
        jax.device_get(imgs)

    def cost_lowered(self, entries):
        from ..parallel.sweep import sweep_phase2

        ctx, carry, ctrl_g = self._template_inputs(entries)
        return sweep_phase2(self.pipe, ctx, carry, ctrl_g,
                            num_steps=self.steps, guidance_scale=1.0,
                            scheduler=self.scheduler, mesh=None,
                            **self._reuse_kw(), progress=self.progress,
                            metrics=self.heartbeat, lower_only=True)

    def __call__(self, entries, guidance: float):
        import jax

        ctx, carry, ctrl = self._inputs(entries)
        imgs, lats = self._run(ctx, carry, ctrl, guidance)
        if self.validate:
            from ..engine.sampler import lane_finite

            self.last_lane_finite = jax.device_get(lane_finite(lats))
        return jax.device_get(imgs)


def default_runner_factory(pipe, progress: bool = False,
                           validate: bool = False, heartbeat: bool = False,
                           mesh=None, semcache=None):
    """The engine's default ``runner_factory``: real sweeps on ``pipe``.
    Dispatches on the compile key's pool tag — ``("phase1", ...)`` /
    ``("phase2", ...)`` keys build the disaggregated pool runners,
    everything else the monolithic :class:`SweepRunner` (ungated traffic's
    bitwise-unchanged fast path). ``mesh`` (a live ``jax.sharding.Mesh``)
    makes every runner dispatch sharded over its dp axis; the engine
    suffixes the cache key with the mesh shape (``serve.meshing.mesh_key``)
    — stripped here, since the runners parse the un-suffixed layout."""

    if mesh is not None:
        # Weight residency: replicate the sweep-side params onto the mesh
        # ONCE, so no dispatch ever pays (or implicitly performs) the
        # device-0 → mesh reshard. Shared by every runner the factory
        # builds.
        from .meshing import replicate_pipeline

        pipe = replicate_pipeline(pipe, mesh)

    def make(compile_key: Tuple, bucket: int):
        from .meshing import strip_mesh_key

        compile_key = strip_mesh_key(compile_key)
        kw = dict(progress=progress, validate=validate, heartbeat=heartbeat,
                  mesh=mesh, semcache=semcache)
        tag = compile_key[0] if compile_key else None
        if tag == "phase1":
            return Phase1Runner(pipe, compile_key, bucket, **kw)
        if tag == "phase2":
            return Phase2Runner(pipe, compile_key, bucket, **kw)
        return SweepRunner(pipe, compile_key, bucket, **kw)

    return make
