"""SLO-tiered multi-tenant scheduling policy for the serve loop.

One FIFO cannot protect premium latency under overload: at millions of
users the engine needs *priority classes*, and the two-pool phase
structure already gives it a preemption point for free (the phase-1 →
phase-2 hand-off is a serializable suspension point whose carry the
journal can spill). This module is the policy vocabulary that the rest of
the stack shares — it deliberately imports nothing from the serve package
so ``request``/``queue``/``engine_loop`` can all depend on it:

- :data:`TIERS` — the closed, ordered set of SLO tiers (best first).
  Bounded by construction: tier is a metric label and a batch-key
  component, so free-text tiers would be unbounded cardinality and
  unbounded program fragmentation.
- :class:`SloConfig` — the scheduler knobs: per-tier weights for
  weighted-fair queuing across tenants, per-tenant outstanding quotas
  (reject kind ``quota``), the phase-boundary preemption thresholds,
  deadline-aware batching (urgent requests flush immediately onto an
  already-warm bucket), and which tiers the degradation ladder must not
  force-gate.
- :class:`FairClock` — deterministic start-time fair queuing: each
  admitted request gets a finish tag ``vtime[tenant] += 1/weight``; the
  queue drains tiers strictly in rank order and, within a tier, tenants
  in finish-tag order — a tenant flooding the queue advances its own
  virtual time and yields to lighter tenants, weighted by tier.

Scheduling metadata NEVER joins a compile key (tiers must not fragment
compiled programs); under an active :class:`SloConfig` the tier joins the
*batch* key only (``engine_loop`` appends it to the batcher ``key_fn``),
so premium lanes never ride behind best-effort batchmates while every
tier still shares one compiled program per bucket. ``slo=None`` (the
default everywhere) is the disabled mode: not a key, a record byte or a
metric family changes — the same discipline as chaos/flight/mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

#: Ordered SLO tiers, best (most protected) first. The index is the tier's
#: rank: lower rank dispatches first, higher rank sheds first.
TIERS = ("premium", "standard", "best_effort")

#: ``Request.priority`` must be an int in ``[-PRIORITY_BOUND,
#: PRIORITY_BOUND]`` — validated at admission (schema reject), never
#: discovered as a ``TypeError`` inside the queue's sort comparator.
PRIORITY_BOUND = 1_000_000

#: ``Request.tenant`` length cap: tenant ids are caller-chosen free text
#: that flows into quota bookkeeping; a cap keeps a hostile id from
#: becoming a memory/log weapon (the id itself is never a metric label).
TENANT_MAX_LEN = 128

_DEFAULT_WEIGHTS = (("premium", 4.0), ("standard", 2.0), ("best_effort", 1.0))


def tier_rank(tier: str) -> int:
    """Rank of a tier (0 = most protected). Raises on unknown tiers —
    the schema validated them at admission, so an unknown tier here is a
    programming error, not traffic."""
    return TIERS.index(tier)


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Scheduler policy for one serve loop. Everything defaults to the
    mildest useful behavior; ``serve_forever(slo=None)`` (the default)
    disables the whole layer.

    - ``tenant_quota`` — max *outstanding* (admitted, unresolved)
      requests per named tenant; excess submissions reject with kind
      ``quota``. Requests without a ``tenant`` field are never
      quota-limited (they are not a tenant).
    - ``preempt_depth`` — when ``queue.outstanding`` exceeds this while
      strictly higher-tier work waits for the batcher, lower-tier
      requests parked between their phases (waiting in the phase-2
      batcher) are preempted: their carry is spilled via the journal's
      hand-off path with a ``preempted`` WAL record, and they resume
      when the pressure clears. ``None`` disables preemption.
    - ``resume_depth`` — outstanding depth at/below which parked work
      resumes (default: ``preempt_depth``). Parked work also resumes
      whenever no higher-tier work is waiting, so a queue made of parked
      requests can never deadlock itself.
    - ``deadline_jump`` — urgent requests (deadline would expire waiting
      out ``max_wait_ms``) flush immediately onto an already-warm bucket
      (the smallest warm one that fits, via warm-preference) instead of
      aging out; never pulls a compile in-band (the jump only fires when
      a warm program already covers the group).
    - ``weights`` — per-tier weighted-fair share across tenants.
    - ``protect_gate_tiers`` — tiers exempt from the level-1 degradation
      force-gate (paid tiers keep full-quality sampling; best-effort
      absorbs the approximation first, exactly as it absorbs the shed).
    - ``default_tier`` — the tier of requests that carry none.
    """

    tenant_quota: Optional[int] = None
    preempt_depth: Optional[int] = None
    resume_depth: Optional[int] = None
    deadline_jump: bool = True
    weights: Tuple[Tuple[str, float], ...] = _DEFAULT_WEIGHTS
    protect_gate_tiers: Tuple[str, ...] = ("premium",)
    default_tier: str = "standard"

    def __post_init__(self):
        if self.tenant_quota is not None and self.tenant_quota < 1:
            raise ValueError(f"tenant_quota must be >= 1, "
                             f"got {self.tenant_quota}")
        if self.preempt_depth is not None and self.preempt_depth < 1:
            raise ValueError(f"preempt_depth must be >= 1, "
                             f"got {self.preempt_depth}")
        if self.resume_depth is not None:
            if self.preempt_depth is None:
                raise ValueError("resume_depth needs preempt_depth")
            if not 0 <= self.resume_depth <= self.preempt_depth:
                raise ValueError(
                    f"resume_depth must be in [0, preempt_depth="
                    f"{self.preempt_depth}], got {self.resume_depth}")
        if self.default_tier not in TIERS:
            raise ValueError(f"default_tier must be one of {TIERS}, "
                             f"got {self.default_tier!r}")
        seen = dict(self.weights)
        for t, w in self.weights:
            if t not in TIERS:
                raise ValueError(f"unknown tier {t!r} in weights; "
                                 f"valid: {TIERS}")
            if w <= 0:
                raise ValueError(f"tier weight must be positive, "
                                 f"got {t}={w}")
        for t in self.protect_gate_tiers:
            if t not in TIERS:
                raise ValueError(f"unknown tier {t!r} in "
                                 f"protect_gate_tiers; valid: {TIERS}")
        object.__setattr__(self, "_weight_map", seen)

    # -- request-facing helpers -------------------------------------------
    def tier(self, req) -> str:
        """The request's effective tier (its field, or the default)."""
        return getattr(req, "tier", None) or self.default_tier

    def rank(self, req) -> int:
        return tier_rank(self.tier(req))

    def weight(self, tier: str) -> float:
        return self._weight_map.get(tier, 1.0)

    @property
    def effective_resume_depth(self) -> Optional[int]:
        if self.preempt_depth is None:
            return None
        return (self.preempt_depth if self.resume_depth is None
                else self.resume_depth)


class FairClock:
    """Deterministic start-time fair queuing over tenants.

    ``tag(tenant, weight)`` charges ``1/weight`` of virtual service to
    the tenant and returns its new virtual finish time — the admission
    queue sorts same-tier entries by this tag, so a heavy tenant's
    requests interleave with (rather than starve) lighter tenants', in
    proportion to their tier weights. Tenant-less requests share one
    anonymous lane (they are already globally FIFO within their tier).
    Purely arithmetic: same admission order ⇒ same tags, byte-stable
    drills."""

    _ANON = ""

    def __init__(self):
        self._vtime: Dict[str, float] = {}

    def tag(self, tenant: Optional[str], weight: float) -> float:
        key = tenant if tenant is not None else self._ANON
        ft = self._vtime.get(key, 0.0) + 1.0 / max(weight, 1e-9)
        self._vtime[key] = ft
        return ft
