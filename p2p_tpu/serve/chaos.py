"""Deterministic fault injection for the serve loop.

The fault-tolerance paths (typed retries, lane isolation, watchdog
timeouts, output validation, fatal drain) are exactly the code that never
runs in a healthy test environment — so they get a harness that *makes*
them run, deterministically. A :class:`FaultPlan` is a seeded schedule
mapping dispatch targets (batch indices or request ids) to fault kinds;
the engine consults it via one hook that is ``None`` in production (the
same discipline as the obs layer: disabled means not a single extra
branch on data, proven by the disabled-mode parity test).

Fault kinds and the path each one drills:

- ``transient`` — raised before the runner executes; classified transient
  → bounded backoff + same-batch retry. Fires **once** per target (a
  flake), so the retry succeeds and the batch's outputs stay bitwise
  identical to the fault-free run.
- ``poison`` — raised whenever the victim request id is in the batch
  (reproducible per-lane failure) → lane-isolation retry; the victim
  resolves ``error``, survivors re-run (warm-preference keeps them in the
  same padded program, so their outputs stay bitwise identical).
- ``hang`` — the runner call sleeps past the watchdog deadline (wall
  clock) → ``timeout`` terminal records + program-cache quarantine.
- ``nan`` — the run succeeds but the victim lane's finite-flag is forced
  false → ``invalid_output`` instead of a shipped black image.
- ``fatal`` — classified fatal → the loop drains with terminal records
  for everything outstanding.

Lifecycle kinds (ISSUE 9) never reach the runner — they drill the drain /
snapshot machinery instead:

- ``sigterm`` — the dispatch where it fires requests a *graceful drain*
  (exactly what a SIGTERM handler does): the batch itself runs normally,
  then the loop stops admitting, finishes in-flight work, snapshots and
  exits with its summary.
- ``kill_during_drain`` — ARMS a process kill that fires after the next
  drain-mode dispatch: :class:`SimulatedKill` propagates out of the
  generator mid-drain (the drill closes the journal's raw handle, like a
  real death), and the restart must still be exactly-once.
- ``kill_during_snapshot`` — ARMS a kill inside the next
  ``journal.compact``: the snapshot is durably renamed but the WAL never
  rotates — the nastiest real crash window, which replay must fold
  idempotently (snapshot ∪ overlapping WAL, duplicates collapsed).
- ``kill_during_resize`` — ARMS a kill inside the next elastic mesh
  resize (ISSUE 19): the journaled ``resize`` record is durable but the
  cutover never completes. The restart must resume on the *target*
  topology the WAL recorded and replay every parked carry exactly-once.

Plans are plain JSON (``{"by_batch": {"3": "transient"}, "by_request":
{"r-07": "poison"}}``) so ``tools/loadgen.py`` can emit them next to a
trace and ``p2p-tpu serve --chaos-plan`` can load them;
:meth:`FaultPlan.generate` derives one deterministically from a seed.
``tools/chaos_drill.py`` asserts the drill invariants end to end.
"""

from __future__ import annotations

import dataclasses
import json
import random
from typing import Callable, Dict, Optional, Sequence, Tuple

#: Lifecycle drill kinds: intercepted by the engine before the runner —
#: ``sigterm`` requests a graceful drain at its dispatch; the ``kill_*``
#: kinds ARM a :class:`SimulatedKill` that fires at the next drain-mode
#: dispatch / inside the next snapshot.
SIGTERM = "sigterm"
KILL_DURING_DRAIN = "kill_during_drain"
KILL_DURING_SNAPSHOT = "kill_during_snapshot"
#: ISSUE 12: force a phase-boundary *preemption* of the keyed dispatch's
#: victims (their carry parks via the spill path with a journaled
#: ``preempted`` record), then die before the parked work resumes — the
#: kill fires at the first batch-boundary sync after the park. The
#: restart must resume the victim in phase 2 off the spill exactly like a
#: crashed hand-off: exactly-once, bitwise-identical outputs.
PREEMPT_THEN_KILL = "preempt_then_kill"
#: ISSUE 13: die between a semantic-cache L3 insert and the leader's
#: terminal fsync — the cache record and result spill are durable, the
#: leader's terminal is not. The restart must reseed the cache off the
#: journaled insert and serve the (still-pending) leader and followers
#: from it: exactly-once, bitwise-identical to the uncached run.
KILL_AFTER_CACHE_INSERT = "kill_after_cache_insert"
#: ISSUE 18: die inside the profiler's batch-boundary finalize — after a
#: sampled capture's trace files are durable in the ring's tmp dir but
#: before the atomic commit rename. The restart must sweep the orphaned
#: ``tmp-cap-*`` dir (the carry-spill GC discipline) and keep serving
#: exactly-once; the ledger merely loses that one capture.
KILL_DURING_CAPTURE = "kill_during_capture"
#: ISSUE 19: die inside an elastic mesh resize — after the ``resize``
#: journal record (old/new topology + parked carry ids) is durably
#: fsync'd but before the cutover completes. The restart must read the
#: WAL-recorded *target* topology, rebuild the mesh at the new dp, and
#: resume every parked carry off its spill: exactly-once terminals,
#: bitwise-identical ok outputs vs an uninterrupted run.
KILL_DURING_RESIZE = "kill_during_resize"


@dataclasses.dataclass(frozen=True)
class ChaosKind:
    """One registered fault kind (ISSUE 20: the single table the kind
    vocabulary, the CLI's inert-kill warnings and walcheck's crash-point
    mapping all derive from — no more hand-maintained parallel lists)."""

    name: str
    #: Fires once then is spent; sticky kinds (poison/nan) keep matching
    #: their victim id.
    one_shot: bool
    #: Drills the drain/snapshot machinery instead of the runner.
    lifecycle: bool = False
    #: The kind ARMS a deferred :class:`SimulatedKill` (``arm_kill``).
    arms_kill: bool = False
    #: The ``analysis/protocol.CRASH_WINDOWS`` entry this kill lands the
    #: WAL in — the walcheck model checker injects a crash at every
    #: instance of that window, so the one-shot drill is the sampled twin
    #: of an exhaustively checked crash point. ``None``: not a crash
    #: (sigterm) or a window outside the WAL protocol (the profiler's
    #: capture ring).
    crash_window: Optional[str] = None
    #: ``(kinds, flags) -> warning or None``: the kind is inert without
    #: its enabling flag(s) — a drill that "passes" without exercising
    #: the path is worse than one that fails, so the CLI says so up
    #: front (``inert_warnings``).
    inert: Optional[Callable] = None


def _inert_nan(kinds, flags):
    if not flags.get("validate_outputs"):
        return ("chaos plan injects 'nan' but --validate-outputs is off — "
                "the injection is inert and the validation path is NOT "
                "being drilled")


def _inert_hang(kinds, flags):
    if flags.get("watchdog_ms") is None:
        return ("chaos plan injects 'hang' but --watchdog-ms is unset — "
                "the hang degrades to a short stall and the watchdog path "
                "is NOT being drilled")


def _inert_kill_during_snapshot(kinds, flags):
    if not flags.get("journal") or flags.get("snapshot_every_ms") is None:
        return ("chaos plan arms 'kill_during_snapshot' but periodic "
                "snapshots are off (--journal + --snapshot-every-ms) — "
                "the kill can only fire at a drain's final snapshot")


def _inert_kill_during_drain(kinds, flags):
    if SIGTERM not in kinds:
        return ("chaos plan arms 'kill_during_drain' with no 'sigterm' to "
                "start a drain — it only fires if the operator drains "
                "(SIGTERM/SIGINT) mid-run")


def _inert_kill_after_cache_insert(kinds, flags):
    if not (flags.get("cache") and flags.get("journal")):
        return ("chaos plan arms 'kill_after_cache_insert' but the insert "
                "window needs --cache AND --journal — the kill never "
                "fires and the durability path is NOT being drilled")


def _inert_kill_during_capture(kinds, flags):
    if not flags.get("profile"):
        return ("chaos plan arms 'kill_during_capture' but --profile is "
                "off — there is no capture to die inside and the "
                "orphan-sweep path is NOT being drilled")


def _inert_kill_during_resize(kinds, flags):
    if flags.get("elastic") is None:
        return ("chaos plan arms 'kill_during_resize' but --elastic is "
                "off — no resize ever runs, the kill never fires and the "
                "mid-resize crash window is NOT being drilled")


#: The chaos-kind registry. Order matters: it is the vocabulary order of
#: ``KINDS`` (error messages, ``--fault-kinds`` docs) — runner kinds
#: first, lifecycle kinds after, both in their historical order.
CATALOG: Dict[str, ChaosKind] = {k.name: k for k in (
    ChaosKind("transient", one_shot=True),
    ChaosKind("poison", one_shot=False),
    ChaosKind("fatal", one_shot=True),
    ChaosKind("hang", one_shot=True, inert=_inert_hang),
    ChaosKind("nan", one_shot=False, inert=_inert_nan),
    ChaosKind(SIGTERM, one_shot=True, lifecycle=True),
    ChaosKind(KILL_DURING_DRAIN, one_shot=True, lifecycle=True,
              arms_kill=True, crash_window="record-boundary",
              inert=_inert_kill_during_drain),
    ChaosKind(KILL_DURING_SNAPSHOT, one_shot=True, lifecycle=True,
              arms_kill=True, crash_window="snapshot-overlap",
              inert=_inert_kill_during_snapshot),
    ChaosKind(PREEMPT_THEN_KILL, one_shot=True, lifecycle=True,
              arms_kill=True, crash_window="record-boundary",
              inert=None),
    ChaosKind(KILL_AFTER_CACHE_INSERT, one_shot=True, lifecycle=True,
              arms_kill=True, crash_window="record-boundary",
              inert=_inert_kill_after_cache_insert),
    ChaosKind(KILL_DURING_CAPTURE, one_shot=True, lifecycle=True,
              arms_kill=True, crash_window=None,
              inert=_inert_kill_during_capture),
    ChaosKind(KILL_DURING_RESIZE, one_shot=True, lifecycle=True,
              arms_kill=True, crash_window="record-boundary",
              inert=_inert_kill_during_resize),
)}

LIFECYCLE_KINDS = tuple(k for k, c in CATALOG.items() if c.lifecycle)

KINDS = tuple(CATALOG)

#: Kinds that fire once and are then spent (a flake / a single hang / one
#: fatal / one lifecycle action). ``poison`` and ``nan`` are properties of
#: the *request* and keep firing as long as the victim id shows up.
_ONE_SHOT = tuple(k for k, c in CATALOG.items() if c.one_shot)

#: Kinds ``arm_kill`` accepts (every lifecycle kind except ``sigterm``,
#: which requests a graceful drain — no kill to arm).
KILL_KINDS = tuple(k for k, c in CATALOG.items() if c.arms_kill)


def inert_warnings(kinds: Sequence[str], flags: dict):
    """The CLI's pre-flight check: for each kind in the plan, the warning
    its catalog entry emits when its enabling flag(s) are off. ``flags``
    carries the raw CLI arg values (``validate_outputs``, ``watchdog_ms``,
    ``journal``, ``snapshot_every_ms``, ``cache``, ``profile``,
    ``elastic``)."""
    kinds = set(kinds)
    out = []
    for name, entry in CATALOG.items():
        if name in kinds and entry.inert is not None:
            msg = entry.inert(kinds, flags)
            if msg:
                out.append(msg)
    return out


class SimulatedKill(Exception):
    """A chaos-injected process death (``kill_during_drain`` /
    ``kill_during_snapshot``): propagates straight out of the serve
    generator — no record, no summary, exactly like SIGKILL as far as the
    journal is concerned. Drills catch it, close the journal's raw handle
    and restart."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One injection decision handed to the engine at dispatch time."""

    kind: str
    target: str            # "batch:<n>" or "request:<id>"
    rids: Tuple[str, ...]  # the victim request ids within this batch


class FaultPlan:
    """Seeded, explicit schedule of injected faults.

    ``by_batch`` keys on the engine's dispatch counter (1-based, including
    isolation re-dispatches — the deterministic control-flow index);
    ``by_request`` keys on request ids. Both are consulted by
    :meth:`take`, batch match first."""

    def __init__(self, by_batch: Optional[Dict[int, str]] = None,
                 by_request: Optional[Dict[str, str]] = None,
                 seed: Optional[int] = None):
        self.by_batch = {int(k): v for k, v in (by_batch or {}).items()}
        self.by_request = dict(by_request or {})
        self.seed = seed
        for kind in list(self.by_batch.values()) + list(self.by_request.values()):
            if kind not in KINDS:
                raise ValueError(f"unknown fault kind {kind!r}; "
                                 f"valid: {', '.join(KINDS)}")
        self._fired: set = set()
        self._armed_kills: set = set()

    def __len__(self) -> int:
        return len(self.by_batch) + len(self.by_request)

    def reset(self) -> None:
        """Forget one-shot firing state (re-run the same plan)."""
        self._fired.clear()
        self._armed_kills.clear()

    # -- lifecycle kills ---------------------------------------------------
    def arm_kill(self, kind: str) -> None:
        """A ``kill_during_*`` fault was taken at its keyed dispatch: the
        kill itself fires later, at the matching lifecycle point (the next
        drain-mode dispatch / the next snapshot's durable moment / the
        batch-boundary sync after a forced preemption)."""
        if kind not in KILL_KINDS:
            raise ValueError(f"not a kill kind: {kind!r}")
        self._armed_kills.add(kind)

    def take_kill(self, kind: str) -> bool:
        """Consume an armed kill of ``kind`` (one-shot); the caller raises
        :class:`SimulatedKill`."""
        if kind in self._armed_kills:
            self._armed_kills.discard(kind)
            return True
        return False

    def take(self, batch_index: int, request_ids: Sequence[str]
             ) -> Optional[Fault]:
        """The fault to inject into this dispatch, or None. One-shot kinds
        are consumed; sticky kinds (poison/nan) keep matching their id."""
        kind = self.by_batch.get(batch_index)
        if kind is not None:
            key = ("batch", batch_index)
            if kind not in _ONE_SHOT or key not in self._fired:
                self._fired.add(key)
                return Fault(kind, f"batch:{batch_index}",
                             tuple(request_ids))
        for rid in request_ids:
            kind = self.by_request.get(rid)
            if kind is None:
                continue
            key = ("request", rid)
            if kind in _ONE_SHOT and key in self._fired:
                continue
            self._fired.add(key)
            return Fault(kind, f"request:{rid}", (rid,))
        return None

    # -- (de)serialization -------------------------------------------------
    def to_dict(self) -> dict:
        out = {"by_batch": {str(k): v for k, v in self.by_batch.items()},
               "by_request": dict(self.by_request)}
        if self.seed is not None:
            out["seed"] = self.seed
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        unknown = set(d) - {"by_batch", "by_request", "seed"}
        if unknown:
            raise ValueError(f"unknown fault-plan field(s) {sorted(unknown)}")
        return cls(by_batch=d.get("by_batch"), by_request=d.get("by_request"),
                   seed=d.get("seed"))

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def generate(cls, seed: int, request_ids: Sequence[str],
                 rate: float = 0.25,
                 kinds: Sequence[str] = ("transient", "poison", "nan"),
                 ) -> "FaultPlan":
        """Deterministic request-targeted plan: each id draws a fault with
        probability ``rate``, kind chosen uniformly from ``kinds`` — same
        seed, same ids ⇒ byte-identical plan (the loadgen contract)."""
        for kind in kinds:
            if kind not in KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
        rng = random.Random(seed)
        by_request = {}
        for rid in request_ids:
            if rng.random() < rate:
                by_request[rid] = kinds[rng.randrange(len(kinds))]
        return cls(by_request=by_request, seed=seed)
