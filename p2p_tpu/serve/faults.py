"""Typed failure taxonomy + retry policy for the serving layer.

Before this module, the serve loop had exactly one failure behavior: any
batch exception triggered the lane-isolation retry. Production failure
modes are not one kind — a transient device error (RESOURCE_EXHAUSTED, a
busy interconnect, an injected flake) deserves the *same* batch again after
a short backoff; a poisoned request must fail alone without taking its
batchmates down (the pre-existing isolation path); a fatal condition (shape
mismatch against the checkpoint, a corrupted program) will fail every batch
forever and the only honest move is to drain the loop with terminal records
for everything outstanding.

:func:`classify` maps an exception to one of the three kinds by type and
message pattern — unknown exceptions default to ``poison`` so the
pre-existing isolation semantics are the fallback, never a behavior change.
:class:`RetryPolicy` is bounded exponential backoff with *deterministic*
jitter (a hash of the retry key and attempt index — no RNG state, so a
replayed trace retries on the identical schedule). The engine charges
backoffs to its virtual clock; :func:`retry_call` is the wall-clock variant
wrapping one-shot host work (checkpoint loading, ``ProgramCache`` builds).

:func:`run_with_watchdog` runs a callable in a daemon worker thread and
bounds it with a *wall-clock* deadline — the only place the serving layer
uses real threads. The virtual clock cannot see a hung compile or device
execution (nothing returns to advance it), so past dispatch the watchdog is
the liveness backstop: on expiry the caller gets :class:`WatchdogTimeout`
(classified ``timeout``) and the worker is abandoned. An optional
``heartbeat`` callable (wired to the compiled loop's step callbacks via
``utils.progress.set_watchdog_sink``) re-arms the deadline while steps are
still flowing, so a long-but-alive batch is never shot.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Callable, Optional

TRANSIENT = "transient"
POISON = "poison"
FATAL = "fatal"
TIMEOUT = "timeout"

#: Message fragments (lowercased) that mark a transient, retry-worthy
#: failure — the device-runtime vocabulary for "try again later".
_TRANSIENT_PATTERNS = (
    "resource_exhausted", "resource exhausted", "device busy", "deadline_exceeded",
    "unavailable", "connection reset", "temporarily", "out of memory",
    "injected transient",
)

#: Fragments that mark a fatal, will-never-succeed failure: the program or
#: its inputs are structurally wrong (checkpoint/shape drift), so retrying
#: any batch is wasted work and the loop must drain. Deliberately narrow:
#: INVALID_ARGUMENT is *not* here — the runtime raises it for per-input
#: problems too, and misreading one poisoned request as fatal would drain
#: the whole server where isolation would have served every survivor.
_FATAL_PATTERNS = (
    "shape mismatch", "checkpoint", "failed_precondition",
    "unimplemented", "injected fatal",
)


class InjectedFault(RuntimeError):
    """A fault raised by the chaos harness (``serve.chaos``); carries its
    intended classification so drills exercise exactly the path they name."""

    def __init__(self, kind: str, target: str = ""):
        super().__init__(f"injected {kind} fault"
                         + (f" ({target})" if target else ""))
        self.kind = kind
        self.target = target


class WatchdogTimeout(RuntimeError):
    """Raised by :func:`run_with_watchdog` when the wall-clock deadline
    passes with no result and no heartbeat progress."""

    def __init__(self, timeout_ms: float, what: str = "batch execution"):
        super().__init__(f"{what} exceeded the {timeout_ms:.0f}ms watchdog "
                         "deadline")
        self.timeout_ms = timeout_ms


class FatalFault(RuntimeError):
    """Wrapper the engine uses to carry a fatal classification upward."""


def classify(exc: BaseException) -> str:
    """Map an exception to ``transient`` / ``poison`` / ``fatal`` /
    ``timeout``.

    Order matters: explicit marker types first (injected faults, watchdog),
    then message patterns, then the ``poison`` default — which is exactly
    the pre-fault-taxonomy behavior (lane isolation), so an exception this
    table has never seen degrades to the old, safe path rather than a new
    one."""
    if isinstance(exc, WatchdogTimeout):
        return TIMEOUT
    if isinstance(exc, InjectedFault):
        return exc.kind if exc.kind in (TRANSIENT, POISON, FATAL) else POISON
    if isinstance(exc, FatalFault):
        return FATAL
    msg = f"{type(exc).__name__}: {exc}".lower()
    for pat in _FATAL_PATTERNS:
        if pat in msg:
            return FATAL
    for pat in _TRANSIENT_PATTERNS:
        if pat in msg:
            return TRANSIENT
    return POISON


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    ``max_attempts`` counts *runs*, not retries: 3 means one initial try
    plus two retries. The jitter is a pure function of ``(key, attempt)`` —
    a blake2b hash scaled into ``[0, jitter_frac]`` of the base delay — so
    two runs of the same trace back off on the identical schedule (the
    chaos drill's determinism contract) while distinct batches still
    de-synchronize."""

    max_attempts: int = 3
    base_ms: float = 50.0
    multiplier: float = 2.0
    max_backoff_ms: float = 2000.0
    jitter_frac: float = 0.25

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, "
                             f"got {self.max_attempts}")

    def backoff_ms(self, attempt: int, key: str = "") -> float:
        """Delay before retry number ``attempt`` (0 = first retry)."""
        base = min(self.max_backoff_ms,
                   self.base_ms * (self.multiplier ** attempt))
        digest = hashlib.blake2b(f"{key}:{attempt}".encode(),
                                 digest_size=8).digest()
        frac = int.from_bytes(digest, "big") / float(2 ** 64)
        return base * (1.0 + self.jitter_frac * frac)


def retry_call(fn: Callable, *, policy: Optional[RetryPolicy] = None,
               key: str = "", sleep: Callable[[float], None] = time.sleep,
               on_retry: Optional[Callable[[int, float, BaseException],
                                           None]] = None):
    """Run ``fn()`` under ``policy``, retrying transient failures with
    wall-clock backoff. Non-transient failures propagate immediately; the
    last transient failure propagates once attempts are exhausted.

    This is the one-shot host-work wrapper (checkpoint loading, program
    builds); the engine loop implements the same policy inline because its
    backoffs are charged to the *virtual* clock."""
    policy = policy or RetryPolicy()
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 — classified, not swallowed
            if classify(exc) != TRANSIENT or attempt + 1 >= policy.max_attempts:
                raise
            delay_ms = policy.backoff_ms(attempt, key)
            if on_retry is not None:
                on_retry(attempt, delay_ms, exc)
            sleep(delay_ms / 1000.0)
    raise AssertionError("unreachable")  # pragma: no cover


def run_with_watchdog(fn: Callable[[], object], timeout_ms: float,
                      heartbeat: Optional[Callable[[], int]] = None,
                      what: str = "batch execution",
                      poll_ms: float = 10.0):
    """Run ``fn()`` in a daemon thread; raise :class:`WatchdogTimeout` if no
    result lands within ``timeout_ms`` of wall time *and* ``heartbeat()``
    (a monotonic progress counter, e.g. compiled-loop step callbacks) has
    not advanced — progress re-arms the deadline. On timeout the worker is
    abandoned (a hung XLA execution cannot be interrupted from Python); its
    eventual result, if any, is discarded.

    Known limitation: an abandoned worker that later *resumes* still emits
    step callbacks through whatever heartbeat sink is globally installed at
    that moment. The engine clears its sink between batches, so stale beats
    while the loop is idle are no-ops — but beats landing during a later
    batch's run can re-arm *that* batch's watchdog, so a second consecutive
    hang may take longer than ``timeout_ms`` to detect."""
    if timeout_ms <= 0:
        raise ValueError(f"watchdog timeout must be positive, got {timeout_ms}")
    result: list = []
    error: list = []
    done = threading.Event()

    def work():
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            error.append(e)
        finally:
            done.set()

    worker = threading.Thread(target=work, daemon=True,
                              name="p2p-serve-watchdog-worker")
    worker.start()
    deadline = time.monotonic() + timeout_ms / 1000.0
    last_beat = heartbeat() if heartbeat is not None else None
    while not done.wait(min(poll_ms / 1000.0, timeout_ms / 1000.0)):
        if heartbeat is not None:
            beat = heartbeat()
            if beat != last_beat:
                last_beat = beat
                deadline = time.monotonic() + timeout_ms / 1000.0
                continue
        if time.monotonic() >= deadline:
            raise WatchdogTimeout(timeout_ms, what)
    if error:
        raise error[0]
    return result[0]
