"""Progress + profiling hooks — the observability layer.

The reference shows a tqdm bar over timesteps (`/root/reference/ptp_utils.py:21,167`)
and a manually-ticked bar over null-text inner iterations
(`/root/reference/null_text.py:578,596-600`). Inside a jitted ``lax.scan``
there is no Python loop to hang a bar on, so progress is reported from the
compiled program via ``jax.debug.callback``: the scan body emits its step
index, and a host-side reporter turns the stream into a single rewriting
line with measured ms/step. The callback is async (no device sync); when
``progress=False`` nothing is traced in, so the silent path's XLA program is
unchanged.

``trace(logdir)`` wraps a block in a ``jax.profiler`` trace — the TPU-native
answer to SURVEY §5's "tracing: none". The resulting directory contains an
xplane + chrome-trace (``*.trace.json.gz``) viewable in Perfetto/TensorBoard.

The same callback channel doubles as the telemetry subsystem's host-event
path (docs/OBSERVABILITY.md): ``emit_step`` carries an optional static
``phase`` tag and ``emit_event`` carries arbitrary traced scalars, both
fanned out to an installable obs sink (``set_obs_sink`` — installed by
``p2p_tpu.obs.device.instrument``) alongside the progress reporter. The
one discipline everything here shares: with ``enabled=False`` *nothing* is
traced into the program — the compiled XLA is bit-identical to a build
that never imported this module.
"""

from __future__ import annotations

import contextlib
import functools
import sys
import time
from typing import Optional

import jax


class StepReporter:
    """Host-side sink for step-index callbacks from a compiled loop.

    Async callbacks can arrive out of order; the reporter tracks the highest
    step seen and smoothed step time. Writes a single rewriting line to
    stderr (a terminal-friendly stand-in for tqdm)."""

    def __init__(self, total: int, label: str = "sampling", stream=None):
        self.total = int(total)
        self.label = label
        self.stream = stream or sys.stderr
        self._last_step = -1
        self._last_t = None
        self._ema_ms = None

    def __call__(self, step) -> None:
        step = int(step)
        now = time.perf_counter()
        if step <= self._last_step:
            return
        if self._last_t is not None and step > 0:
            dt_ms = (now - self._last_t) / max(1, step - self._last_step) * 1000
            self._ema_ms = (dt_ms if self._ema_ms is None
                            else 0.7 * self._ema_ms + 0.3 * dt_ms)
        self._last_step = step
        self._last_t = now
        rate = f" {self._ema_ms:6.1f} ms/step" if self._ema_ms else ""
        self.stream.write(f"\r{self.label}: step {step + 1}/{self.total}{rate}")
        self.stream.flush()
        if step + 1 >= self.total:
            self.stream.write("\n")


# The compiled program must not bake a particular reporter instance in (the
# jit cache outlives any one call), so the traced callback targets this
# module-level slot; callers install their reporter just before launching.
_active: Optional[StepReporter] = None


def set_active(reporter: Optional[StepReporter]) -> None:
    global _active
    _active = reporter


def activate(total: int, label: str = "sampling") -> None:
    """Install a fresh reporter for a progress-enabled launch, first
    draining any still-in-flight callbacks from a previous progress run
    (dispatch is async) so late steps can't poison the new reporter's
    monotonic step filter. The one place the drain-then-install discipline
    lives — used by ``text2image``, ``invert`` phases, and ``sweep``."""
    jax.effects_barrier()
    set_active(StepReporter(int(total), label))


# Secondary sink alongside the rewriting-line reporter: the serve engine
# installs a per-batch hook here to turn the same compiled-loop callback
# stream into per-request step progress records (engine_loop.run_entries),
# without disturbing whatever reporter is active.
_step_hook = None


def set_step_hook(fn) -> None:
    """Install (or clear, with ``None``) a callable invoked with every step
    index the compiled loop emits, in addition to the active reporter."""
    global _step_hook
    _step_hook = fn


# Third sink: the telemetry collector (p2p_tpu.obs.device.StepCollector),
# called as sink("step", step_index, phase) for step callbacks and
# sink(tag, value, None) for generic emit_event events. Installed only for
# the duration of an instrumented run — None costs one load + is-None test.
_obs_sink = None


def set_obs_sink(fn) -> None:
    """Install (or clear, with ``None``) the telemetry sink receiving every
    step/event callback the compiled loops emit."""
    global _obs_sink
    _obs_sink = fn


# Fourth sink: the serve watchdog's heartbeat (p2p_tpu.serve.faults).
# Called with no arguments on every step callback, regardless of the
# report flag — a compiled loop still emitting steps is alive, however
# slow, so the dispatch-time watchdog re-arms instead of shooting it; a
# hung compile/execute emits nothing and the deadline stands.
_watchdog_sink = None


def set_watchdog_sink(fn) -> None:
    """Install (or clear, with ``None``) a zero-arg callable invoked on
    every step callback — the serve watchdog's liveness heartbeat."""
    global _watchdog_sink
    _watchdog_sink = fn


def _dispatch(step, phase=None, report=True) -> None:
    # report=False: a metrics-only emission — the progress surfaces
    # (rewriting-line reporter, serve step hook) must stay silent. Nothing
    # clears _active between runs (dispatch is async; there is no reliable
    # "last callback delivered" moment), so a stale reporter from an
    # earlier progress run would otherwise write garbled lines during a
    # later quiet-but-instrumented run.
    if report:
        r = _active
        if r is not None:
            r(step)
        h = _step_hook
        if h is not None:
            h(step)
    s = _obs_sink
    if s is not None:
        s("step", int(step), phase)
    w = _watchdog_sink
    if w is not None:
        w()


def emit_step(enabled: bool, step, phase: Optional[str] = None,
              report: bool = True) -> None:
    """Trace-time: emit ``step`` to the active reporter (and the obs sink)
    from inside a jitted loop. ``phase`` is a *static* tag naming which scan
    emitted the step ('phase1'/'phase2' for the gated sampler, 'invert'/
    'null_text' for inversion) — it is baked into the host callback, never
    traced. ``report=False`` (metrics-only emission: telemetry on, progress
    off) bypasses the reporter/step-hook surfaces and feeds only the obs
    sink. With ``enabled=False`` nothing is traced in — the compiled
    program is identical to the silent one."""
    if enabled:
        cb = (_dispatch if (phase is None and report)
              else functools.partial(_dispatch, phase=phase, report=report))
        jax.debug.callback(cb, step, ordered=False)


def _dispatch_event(tag, value) -> None:
    s = _obs_sink
    if s is not None:
        s(tag, value, None)


def emit_event(enabled: bool, tag: str, value) -> None:
    """Trace-time: emit a generic ``(tag, value)`` host event from inside a
    jitted program — ``tag`` static, ``value`` traced (e.g. the null-text
    inner-iteration count). Same contract as ``emit_step``: disabled means
    nothing is traced in."""
    if enabled:
        jax.debug.callback(functools.partial(_dispatch_event, tag), value,
                           ordered=False)


@contextlib.contextmanager
def trace(logdir: Optional[str]):
    """``with trace("/tmp/p2p_trace"): ...`` — jax.profiler trace of the
    block; no-op when ``logdir`` is falsy. NOTE (axon-tunneled TPU): stopping
    a trace can wedge the chip lease for a while; profile at the end of a
    session."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
