"""Progress + profiling hooks — the observability layer.

The reference shows a tqdm bar over timesteps (`/root/reference/ptp_utils.py:21,167`)
and a manually-ticked bar over null-text inner iterations
(`/root/reference/null_text.py:578,596-600`). Inside a jitted ``lax.scan``
there is no Python loop to hang a bar on, so progress is reported from the
compiled program via ``jax.debug.callback``: the scan body emits its step
index, and a host-side reporter turns the stream into a single rewriting
line with measured ms/step. The callback is async (no device sync); when
``progress=False`` nothing is traced in, so the silent path's XLA program is
unchanged.

``trace(logdir)`` wraps a block in a ``jax.profiler`` trace — the TPU-native
answer to SURVEY §5's "tracing: none". The resulting directory contains an
xplane + chrome-trace (``*.trace.json.gz``) viewable in Perfetto/TensorBoard.
"""

from __future__ import annotations

import contextlib
import sys
import time
from typing import Optional

import jax


class StepReporter:
    """Host-side sink for step-index callbacks from a compiled loop.

    Async callbacks can arrive out of order; the reporter tracks the highest
    step seen and smoothed step time. Writes a single rewriting line to
    stderr (a terminal-friendly stand-in for tqdm)."""

    def __init__(self, total: int, label: str = "sampling", stream=None):
        self.total = int(total)
        self.label = label
        self.stream = stream or sys.stderr
        self._last_step = -1
        self._last_t = None
        self._ema_ms = None

    def __call__(self, step) -> None:
        step = int(step)
        now = time.perf_counter()
        if step <= self._last_step:
            return
        if self._last_t is not None and step > 0:
            dt_ms = (now - self._last_t) / max(1, step - self._last_step) * 1000
            self._ema_ms = (dt_ms if self._ema_ms is None
                            else 0.7 * self._ema_ms + 0.3 * dt_ms)
        self._last_step = step
        self._last_t = now
        rate = f" {self._ema_ms:6.1f} ms/step" if self._ema_ms else ""
        self.stream.write(f"\r{self.label}: step {step + 1}/{self.total}{rate}")
        self.stream.flush()
        if step + 1 >= self.total:
            self.stream.write("\n")


# The compiled program must not bake a particular reporter instance in (the
# jit cache outlives any one call), so the traced callback targets this
# module-level slot; callers install their reporter just before launching.
_active: Optional[StepReporter] = None


def set_active(reporter: Optional[StepReporter]) -> None:
    global _active
    _active = reporter


def activate(total: int, label: str = "sampling") -> None:
    """Install a fresh reporter for a progress-enabled launch, first
    draining any still-in-flight callbacks from a previous progress run
    (dispatch is async) so late steps can't poison the new reporter's
    monotonic step filter. The one place the drain-then-install discipline
    lives — used by ``text2image``, ``invert`` phases, and ``sweep``."""
    jax.effects_barrier()
    set_active(StepReporter(int(total), label))


# Secondary sink alongside the rewriting-line reporter: the serve engine
# installs a per-batch hook here to turn the same compiled-loop callback
# stream into per-request step progress records (engine_loop.run_entries),
# without disturbing whatever reporter is active.
_step_hook = None


def set_step_hook(fn) -> None:
    """Install (or clear, with ``None``) a callable invoked with every step
    index the compiled loop emits, in addition to the active reporter."""
    global _step_hook
    _step_hook = fn


def _dispatch(step) -> None:
    r = _active
    if r is not None:
        r(step)
    h = _step_hook
    if h is not None:
        h(step)


def emit_step(enabled: bool, step) -> None:
    """Trace-time: emit ``step`` to the active reporter from inside a jitted
    loop. With ``enabled=False`` nothing is traced in — the compiled program
    is identical to the silent one."""
    if enabled:
        jax.debug.callback(_dispatch, step, ordered=False)


@contextlib.contextmanager
def trace(logdir: Optional[str]):
    """``with trace("/tmp/p2p_trace"): ...`` — jax.profiler trace of the
    block; no-op when ``logdir`` is falsy. NOTE (axon-tunneled TPU): stopping
    a trace can wedge the chip lease for a while; profile at the end of a
    session."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
