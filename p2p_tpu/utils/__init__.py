from .tokenizer import ClipBpeTokenizer, HashWordTokenizer, Tokenizer, pad_ids, token_strings

__all__ = ["ClipBpeTokenizer", "HashWordTokenizer", "Tokenizer", "pad_ids", "token_strings"]
