"""Visualization & analysis of stored attention — the observability surface.

Behavioral spec: `/root/reference/ptp_utils.py:24-62` (`text_under_image`,
`view_images`) and `/root/reference/main.py:293-350` (`aggregate_attention`,
`show_cross_attention`, `show_self_attention_comp`). These operate on the
averaged attention store, host-side numpy — they are debug outputs, not part
of the compiled path.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..controllers.base import AttnLayout, StoreState


def text_under_image(image: np.ndarray, text: str,
                     text_color: Tuple[int, int, int] = (0, 0, 0)) -> np.ndarray:
    """Append a caption strip under an image (`/root/reference/ptp_utils.py:24-34`)."""
    h, w, c = image.shape
    offset = int(h * 0.2)
    img = np.ones((h + offset, w, c), dtype=np.uint8) * 255
    img[:h] = image
    try:
        import cv2

        font = cv2.FONT_HERSHEY_SIMPLEX
        textsize = cv2.getTextSize(text, font, 1, 2)[0]
        text_x, text_y = (w - textsize[0]) // 2, h + offset - textsize[1] // 2
        cv2.putText(img, text, (text_x, text_y), font, 1, text_color, 2)
    except ImportError:  # pragma: no cover
        from PIL import Image, ImageDraw

        pil = Image.fromarray(img)
        draw = ImageDraw.Draw(pil)
        tw = draw.textlength(text)
        draw.text(((w - tw) // 2, h + offset // 4), text, fill=text_color)
        img = np.array(pil)
    return img


def view_images(images, num_rows: int = 1, offset_ratio: float = 0.02,
                save_path: Optional[str] = None, show: bool = False) -> np.ndarray:
    """Compose a grid (`/root/reference/ptp_utils.py:37-62`). Returns the
    composed uint8 array; optionally saves/shows instead of requiring a
    notebook display hook."""
    if isinstance(images, np.ndarray) and images.ndim == 4:
        images = [images[i] for i in range(images.shape[0])]
    else:
        images = [np.asarray(im) for im in images]
    # Pad to a full grid (the reference computes `len % num_rows`,
    # `/root/reference/ptp_utils.py:40`, which under-pads and silently drops
    # trailing images for some counts — fixed by design).
    num_empty = (num_rows - len(images) % num_rows) % num_rows

    empty = np.ones_like(images[0]) * 255
    images = [np.asarray(im, dtype=np.uint8) for im in images] + [empty] * num_empty
    num_items = len(images)

    h, w, c = images[0].shape
    offset = int(h * offset_ratio)
    num_cols = num_items // num_rows
    grid = np.ones((h * num_rows + offset * (num_rows - 1),
                    w * num_cols + offset * (num_cols - 1), 3), dtype=np.uint8) * 255
    for i in range(num_rows):
        for j in range(num_cols):
            grid[i * (h + offset): i * (h + offset) + h,
                 j * (w + offset): j * (w + offset) + w] = images[i * num_cols + j]

    if save_path is not None:
        from PIL import Image

        Image.fromarray(grid).save(save_path)
    if show:  # pragma: no cover
        from PIL import Image

        Image.fromarray(grid).show()
    return grid


def aggregate_attention(layout: AttnLayout, state: StoreState, num_steps: int,
                        res: int, from_where: Sequence[str], is_cross: bool,
                        select: int) -> np.ndarray:
    """Average stored maps of one resolution across layers & heads
    (`/root/reference/main.py:293-307`). Returns (res, res, K)."""
    out = []
    for m in layout.stored_metas():
        if m.is_cross != is_cross or m.resolution != res or m.place not in from_where:
            continue
        acc = np.asarray(state[m.store_slot]) / num_steps    # (B, heads, P, K)
        maps = acc[select].reshape(-1, res, res, acc.shape[-1])
        out.append(maps)
    if not out:
        raise ValueError(f"no stored {'cross' if is_cross else 'self'} maps at "
                         f"resolution {res} from {from_where}")
    return np.concatenate(out, axis=0).mean(0)


def show_cross_attention(tokenizer, prompt: str, layout: AttnLayout,
                         state: StoreState, num_steps: int, res: int,
                         from_where: Sequence[str], select: int = 0,
                         save_path: Optional[str] = None) -> np.ndarray:
    """Per-token attention heatmaps with decoded-token captions
    (`/root/reference/main.py:310-327`)."""
    from PIL import Image

    ids = tokenizer.encode(prompt)
    decoder = lambda t: tokenizer.decode([t])
    maps = aggregate_attention(layout, state, num_steps, res, from_where, True,
                               select)
    # Sampling truncates prompts to the context length via pad_ids; the raw
    # encode here is unpadded/untruncated, so clamp to the stored K or an
    # over-long prompt would IndexError after the whole expensive run.
    ids = ids[:maps.shape[-1]]
    images = []
    for i in range(len(ids)):
        m = maps[:, :, i]
        m = 255 * m / (m.max() + 1e-12)
        m = np.tile(m[:, :, None], (1, 1, 3)).astype(np.uint8)
        m = np.array(Image.fromarray(m).resize((256, 256)))
        m = text_under_image(m, decoder(int(ids[i])))
        images.append(m)
    return view_images(np.stack(images, axis=0), save_path=save_path)


def show_self_attention_comp(layout: AttnLayout, state: StoreState,
                             num_steps: int, res: int,
                             from_where: Sequence[str], max_com: int = 10,
                             select: int = 0,
                             save_path: Optional[str] = None) -> np.ndarray:
    """Top-k SVD components of the (res², res²) self-attention matrix
    (`/root/reference/main.py:330-350`)."""
    from PIL import Image

    attn = aggregate_attention(layout, state, num_steps, res, from_where, False,
                               select).astype(np.float64).reshape(res * res, res * res)
    u, s, vh = np.linalg.svd(attn - attn.mean(1, keepdims=True))
    images = []
    for i in range(max_com):
        image = vh[i].reshape(res, res)
        image = image - image.min()
        image = 255 * image / image.max()
        image = np.tile(image[:, :, None], (1, 1, 3)).astype(np.uint8)
        image = np.array(Image.fromarray(image).resize((256, 256)))
        images.append(image)
    return view_images(np.stack(images, axis=0), save_path=save_path)
