"""Tokenizer protocol and implementations.

The reference uses the HuggingFace CLIP tokenizer (`/root/reference/main.py:30`)
purely through three operations: `encode(text) -> [ids]` (with BOS/EOS),
per-token `decode([id]) -> str` (used by word-index lookup,
`/root/reference/ptp_utils.py:253`), and fixed-length padding to 77 tokens.
We define that surface as a small protocol so the alignment / controller
precompute layer is tokenizer-agnostic:

- ``ClipBpeTokenizer`` — a self-contained CLIP byte-pair-encoding tokenizer
  that loads ``vocab.json`` + ``merges.txt`` from a local checkpoint directory
  (no network access required at runtime).
- ``HashWordTokenizer`` — a deterministic, vocab-free word tokenizer used by
  tests and random-weight benchmarks: every whitespace word maps to a stable
  id; longer words may split into multiple sub-tokens to exercise the
  multi-token alignment paths.
"""

from __future__ import annotations

import gzip
import hashlib
import json
import os
import unicodedata
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Sequence, Tuple


class Tokenizer(Protocol):
    """The minimal tokenizer surface the framework depends on."""

    bos_token_id: int
    eos_token_id: int
    model_max_length: int

    def encode(self, text: str) -> List[int]:
        """Tokenize to ids, including BOS and EOS (unpadded)."""
        ...

    def decode(self, ids: Sequence[int]) -> str:
        """Inverse of encode for a list of ids (special tokens included)."""
        ...


def pad_ids(ids: Sequence[int], max_length: int, pad_id: int) -> List[int]:
    """Pad/truncate to ``max_length``; truncation keeps EOS as the final token
    (mirrors HF ``padding='max_length', truncation=True`` as used at
    `/root/reference/ptp_utils.py:144-150`)."""
    ids = list(ids)
    if len(ids) > max_length:
        ids = ids[: max_length - 1] + [ids[-1]]
    return ids + [pad_id] * (max_length - len(ids))


def token_strings(tokenizer: Tokenizer, text: str) -> List[str]:
    """Per-token decoded strings for the interior (non-special) tokens.

    Matches ``[tokenizer.decode([t]).strip('#') for t in encode(text)][1:-1]``
    at `/root/reference/ptp_utils.py:253`, additionally stripping the CLIP
    end-of-word marker ``</w>`` so accumulated lengths line up with the raw
    words (the HF CLIP tokenizer's decode already drops it; ours keeps the
    marker internally for exact round-trips).
    """
    ids = tokenizer.encode(text)[1:-1]
    out = []
    for tok in ids:
        s = tokenizer.decode([tok]).strip("#").replace("</w>", "").strip()
        out.append(s)
    return out


# ---------------------------------------------------------------------------
# HashWordTokenizer — deterministic, vocab-free (tests / random-weight bench)
# ---------------------------------------------------------------------------


@dataclass
class HashWordTokenizer:
    """Deterministic word-level tokenizer with optional sub-word splitting.

    Words hash into ``[num_special, vocab_size)``; words longer than
    ``split_len`` are split into chunks so that multi-token words exist (the
    alignment code's interesting cases — `/root/reference/seq_aligner.py:169`
    — need them). Decoding is exact via a reverse map that is populated on
    encode; unknown ids decode to a stable placeholder.
    """

    vocab_size: int = 49408
    model_max_length: int = 77
    split_len: int = 8
    bos_token_id: int = 0
    eos_token_id: int = 1
    pad_token_id: int = 1  # CLIP pads with EOS
    sequential: bool = False  # collision-free ids, first-seen order
    _reverse: Dict[int, str] = field(default_factory=dict)
    _forward: Dict[str, int] = field(default_factory=dict)

    def _piece_id(self, piece: str) -> int:
        if self.sequential:
            # Collision-free by construction: ids hand out sequentially in
            # first-seen order. Ids are stable within an instance (bench and
            # dryrun build one tokenizer and fixed prompts), not across
            # instances — use the default hash mode when cross-instance id
            # stability matters.
            rid = self._forward.get(piece)
            if rid is None:
                rid = 2 + len(self._forward)
                if rid >= self.vocab_size:
                    raise ValueError(
                        f"HashWordTokenizer vocab exhausted at {piece!r}")
                self._forward[piece] = rid
                self._reverse[rid] = piece
            return rid
        # Purely a function of the piece — ids are identical across instances
        # and encode orders. Collisions (≈50% odds only past ~260 distinct
        # pieces) fail loudly rather than silently remapping.
        h = hashlib.sha1(piece.encode("utf-8")).digest()
        rid = 2 + int.from_bytes(h[:4], "big") % (self.vocab_size - 2)
        prev = self._reverse.setdefault(rid, piece)
        if prev != piece:
            raise ValueError(
                f"HashWordTokenizer id collision: {piece!r} vs {prev!r} (id {rid}); "
                "use ClipBpeTokenizer or a larger vocab_size for this corpus."
            )
        return rid

    def _word_pieces(self, word: str) -> List[str]:
        if len(word) <= self.split_len:
            return [word]
        return [word[i : i + self.split_len] for i in range(0, len(word), self.split_len)]

    def encode(self, text: str) -> List[int]:
        ids = [self.bos_token_id]
        for word in text.lower().split():
            for piece in self._word_pieces(word):
                ids.append(self._piece_id(piece))
        ids.append(self.eos_token_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        parts = []
        for i in ids:
            if i == self.bos_token_id or i == self.eos_token_id:
                continue
            parts.append(self._reverse.get(int(i), f"<unk{int(i)}>"))
        return " ".join(parts)

    def __call__(self, texts, padding: str = "max_length", max_length: Optional[int] = None,
                 truncation: bool = True):
        """HF-style batch call returning ``{'input_ids': [[int]]}``."""
        if isinstance(texts, str):
            texts = [texts]
        max_length = max_length or self.model_max_length
        batch = [pad_ids(self.encode(t), max_length, self.pad_token_id) for t in texts]
        return {"input_ids": batch}


# ---------------------------------------------------------------------------
# ClipBpeTokenizer — real CLIP BPE, loaded from local vocab files
# ---------------------------------------------------------------------------


# CLIP's word-splitting pattern (public, from the CLIP paper's released code).
# Prefer the `regex` module for true Unicode classes; fall back to an
# ASCII-approximate pattern when only stdlib `re` is available (non-ASCII
# words then split per-character — fine for the hash tokenizer / tests, but
# real-checkpoint use should have `regex` installed).
try:
    import regex as _re_mod

    _CLIP_PAT = _re_mod.compile(
        r"<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+",
        _re_mod.IGNORECASE,
    )
except ImportError:  # pragma: no cover
    import re as _re_mod

    _CLIP_PAT = _re_mod.compile(
        r"<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|[a-zA-Z]+|[0-9]|[^\sa-zA-Z0-9]+",
        _re_mod.IGNORECASE,
    )


def _strip_controls_pad_cjk(text: str) -> str:
    """Shared normalization pre-pass (HF BasicTokenizer semantics): drop
    control chars / U+FFFD, space-pad CJK ideographs, fold whitespace chars
    to plain spaces. Used by both the CLIP and BERT tokenizers — keep in one
    place so Unicode edge-case fixes can't diverge."""
    out = []
    for ch in text:
        cp = ord(ch)
        if cp == 0 or cp == 0xFFFD or (unicodedata.category(ch).startswith("C")
                                       and ch not in "\t\n\r"):
            continue
        if _is_cjk(cp):
            out.append(f" {ch} ")
        elif ch in "\t\n\r" or unicodedata.category(ch) == "Zs":
            out.append(" ")
        else:
            out.append(ch)
    return "".join(out)


def _is_cjk(cp: int) -> bool:
    """CJK ideograph ranges (the set HF's BasicTokenizer space-pads)."""
    return (
        0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F
    )


def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2/CLIP reversible byte→unicode table (standard public algorithm)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def _get_pairs(word: Tuple[str, ...]):
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class ClipBpeTokenizer:
    """CLIP's lower-cased byte-level BPE, loading vocab/merges from disk.

    Point it at a local ``tokenizer/`` directory of an SD checkpoint
    (``vocab.json`` + ``merges.txt``); nothing is fetched from the network.
    """

    def __init__(self, vocab_path: str, merges_path: str, model_max_length: int = 77):
        with open(vocab_path, "r", encoding="utf-8") as f:
            self.encoder: Dict[str, int] = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        opener = gzip.open if merges_path.endswith(".gz") else open
        with opener(merges_path, "rt", encoding="utf-8") as f:
            merges = f.read().split("\n")
        merges = [tuple(m.split()) for m in merges if m and not m.startswith("#version")]
        self.bpe_ranks = dict(zip(merges, range(len(merges))))
        self.byte_encoder = _bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.cache: Dict[str, str] = {}
        self.model_max_length = model_max_length
        self.bos_token_id = self.encoder.get("<|startoftext|>", 49406)
        self.eos_token_id = self.encoder.get("<|endoftext|>", 49407)
        self.pad_token_id = self.eos_token_id

    @classmethod
    def from_dir(cls, path: str, **kw) -> "ClipBpeTokenizer":
        return cls(os.path.join(path, "vocab.json"), os.path.join(path, "merges.txt"), **kw)

    def _bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        pairs = _get_pairs(word)
        if not pairs:
            return token + "</w>"
        while True:
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if i < len(word) - 1 and word[i] == first and word[i + 1] == second:
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = " ".join(word)
        self.cache[token] = out
        return out

    def _basic_clean(self, text: str) -> List[str]:
        """Normalize exactly as ``transformers.CLIPTokenizer`` does without
        ftfy (its BasicTokenizer path, strip_accents=False,
        do_split_on_punc=False): drop control chars, space-pad CJK ideographs,
        NFC-normalize, whitespace-split, lowercase. Golden-tested against the
        HF tokenizer in tests/test_tokenizer.py."""
        text = unicodedata.normalize("NFC", _strip_controls_pad_cjk(text))
        text = " ".join(w.lower() for w in text.split())
        return _CLIP_PAT.findall(text)

    def encode(self, text: str) -> List[int]:
        # OOV subwords map to the unk token (= <|endoftext|>), matching HF's
        # CLIPTokenizer unk_token default rather than raising KeyError. With a
        # full CLIP vocab (all 256 byte symbols present) this never triggers.
        unk = self.eos_token_id
        ids = [self.bos_token_id]
        for token in self._basic_clean(text):
            token = "".join(self.byte_encoder[b] for b in token.encode("utf-8"))
            ids.extend(self.encoder.get(t, unk) for t in self._bpe(token).split(" "))
        ids.append(self.eos_token_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        text = "".join(self.decoder.get(int(i), "") for i in ids)
        text = text.replace("<|startoftext|>", "").replace("<|endoftext|>", "")
        data = bytearray(self.byte_decoder[c] for c in text if c in self.byte_decoder)
        return data.decode("utf-8", errors="replace").replace("</w>", " ").strip()

    def __call__(self, texts, padding: str = "max_length", max_length: Optional[int] = None,
                 truncation: bool = True):
        if isinstance(texts, str):
            texts = [texts]
        max_length = max_length or self.model_max_length
        batch = [pad_ids(self.encode(t), max_length, self.pad_token_id) for t in texts]
        return {"input_ids": batch}


# ---------------------------------------------------------------------------
# BertWordPieceTokenizer — the LDM-256 backend's text tokenizer
# ---------------------------------------------------------------------------


class BertWordPieceTokenizer:
    """bert-base-uncased WordPiece, loading ``vocab.txt`` from disk.

    The LDM-256 pipeline tokenizes with the BERT tokenizer before its
    `model.bert` encoder (`/root/reference/ptp_utils.py:112-116`). Surface
    matches :class:`Tokenizer`: ``encode`` wraps in [CLS]/[SEP] (= bos/eos),
    pads with [PAD]=0; per-token ``decode`` yields "##"-prefixed subwords that
    the word-index lookup strips (`/root/reference/ptp_utils.py:253` does
    ``.strip("#")`` precisely for this). Normalization mirrors HF's
    BasicTokenizer for the uncased model: lower-case, strip accents, split
    punctuation, space-pad CJK. Golden-tested vs ``transformers.BertTokenizer``
    in tests/test_tokenizer.py.
    """

    def __init__(self, vocab_path: str, model_max_length: int = 77):
        self.vocab: Dict[str, int] = {}
        with open(vocab_path, "r", encoding="utf-8") as f:
            for line in f:
                tok = line.rstrip("\n")
                if tok:
                    self.vocab[tok] = len(self.vocab)
        self.ids_to_tokens = {v: k for k, v in self.vocab.items()}
        self.model_max_length = model_max_length
        self.bos_token_id = self.vocab["[CLS]"]
        self.eos_token_id = self.vocab["[SEP]"]
        self.pad_token_id = self.vocab["[PAD]"]
        self.unk_token_id = self.vocab["[UNK]"]
        self.max_chars_per_word = 100

    @classmethod
    def from_dir(cls, path: str, **kw) -> "BertWordPieceTokenizer":
        return cls(os.path.join(path, "vocab.txt"), **kw)

    @staticmethod
    def _is_punct(ch: str) -> bool:
        cp = ord(ch)
        if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96 or 123 <= cp <= 126):
            return True
        return unicodedata.category(ch).startswith("P")

    def _basic_tokenize(self, text: str) -> List[str]:
        words = _strip_controls_pad_cjk(text).split()
        tokens: List[str] = []
        for w in words:
            w = w.lower()
            # strip accents (uncased model): NFD then drop Mn marks
            w = "".join(c for c in unicodedata.normalize("NFD", w)
                        if unicodedata.category(c) != "Mn")
            # split on punctuation, keeping each punct char as its own token
            cur = ""
            for ch in w:
                if self._is_punct(ch):
                    if cur:
                        tokens.append(cur)
                        cur = ""
                    tokens.append(ch)
                else:
                    cur += ch
            if cur:
                tokens.append(cur)
        return tokens

    def _wordpiece(self, word: str) -> List[int]:
        if len(word) > self.max_chars_per_word:
            return [self.unk_token_id]
        ids: List[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_token_id]  # whole word becomes [UNK]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str) -> List[int]:
        ids = [self.bos_token_id]
        for word in self._basic_tokenize(text):
            ids.extend(self._wordpiece(word))
        ids.append(self.eos_token_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        toks = [self.ids_to_tokens.get(int(i), "[UNK]") for i in ids
                if int(i) not in (self.bos_token_id, self.eos_token_id,
                                  self.pad_token_id)]
        text = " ".join(toks).replace(" ##", "")
        return text

    def __call__(self, texts, padding: str = "max_length",
                 max_length: Optional[int] = None, truncation: bool = True):
        if isinstance(texts, str):
            texts = [texts]
        max_length = max_length or self.model_max_length
        batch = [pad_ids(self.encode(t), max_length, self.pad_token_id)
                 for t in texts]
        return {"input_ids": batch}
