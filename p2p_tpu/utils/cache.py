"""Persistent XLA compile cache shared by every entry point.

The SD-1.4 sampling program takes minutes of host-side XLA compilation; the
reference pays the analogous torch/diffusers warmup every process start. With
a persistent cache, bench.py / the CLI / the profiling tools compile each
distinct program once per machine and reload it afterwards (works for both
the CPU and TPU backends; keyed on HLO + compile options + backend).

tests/conftest.py sets the same directory via env vars before ``import jax``;
this helper is the post-import equivalent for non-test entry points.
"""

from __future__ import annotations

import hashlib
import os
import sys

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache")


def default_cache_dir(hash_xla_flags: bool = True) -> str:
    """The cache directory every entry point (and test conftest/subprocess
    env) should agree on: a pre-set ``JAX_COMPILATION_CACHE_DIR`` env var
    verbatim — so CI and multi-checkout machines can share ONE cache instead
    of each clone growing its own ``.jax_cache`` — else the repo-local
    default, suffixed with a hash of the ambient ``XLA_FLAGS`` (not every XLA
    flag reaches the cache key, so two processes with different codegen flags
    must never reload each other's executables). jax-free, so test conftests
    can call it before their first ``import jax``."""
    env_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if env_dir:
        return env_dir
    flags = os.environ.get("XLA_FLAGS", "") if hash_xla_flags else ""
    suffix = ("-" + hashlib.sha256(flags.encode()).hexdigest()[:12]
              if flags else "")
    return _DEFAULT_DIR + suffix


_ENSURED: dict = {}


def ensure_persistent_cache() -> str | None:
    """:func:`enable_persistent_cache` exactly once per process.

    Long-lived entry points (the serve loop's program cache, anything that
    builds programs repeatedly) want the persistent XLA cache on without
    re-running the setup — or re-printing its failure warning — per call.
    Returns the cache dir of the first (and only) attempt, None if that
    attempt failed.
    """
    if "dir" not in _ENSURED:
        _ENSURED["dir"] = enable_persistent_cache()
    return _ENSURED["dir"]


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir`` (defaults to
    :func:`default_cache_dir` — a pre-set ``JAX_COMPILATION_CACHE_DIR``, else
    ``<repo>/.jax_cache``, gitignored). Safe to call more than once.

    The ``JAX_PERSISTENT_CACHE_*`` env knobs are honored when set. The cache
    is a pure optimization: any failure to set it up is reported and skipped.
    """
    import jax

    if cache_dir is None:
        cache_dir = default_cache_dir()
    try:
        # Parse everything before the first config.update so the settings
        # apply all-or-nothing (a late parse error must not leave the cache
        # half-enabled while we report it disabled).
        min_secs = float(
            os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", 1.0))
        min_bytes = int(
            os.environ.get("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", 0))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", min_bytes)
    except Exception as e:  # cache must never take an entry point down
        print(f"persistent compile cache disabled ({type(e).__name__}: {e})",
              file=sys.stderr)
        return None
    return cache_dir
