"""Persistent XLA compile cache shared by every entry point.

The SD-1.4 sampling program takes minutes of host-side XLA compilation; the
reference pays the analogous torch/diffusers warmup every process start. With
a persistent cache, bench.py / the CLI / the profiling tools compile each
distinct program once per machine and reload it afterwards (works for both
the CPU and TPU backends; keyed on HLO + compile options + backend).

tests/conftest.py sets the same directory via env vars before ``import jax``;
this helper is the post-import equivalent for non-test entry points.
"""

from __future__ import annotations

import hashlib
import os
import sys

import jax

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache")


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir`` (defaults to
    ``<repo>/.jax_cache``, gitignored). Safe to call more than once.

    Not every XLA flag reaches the cache key, so the ambient ``XLA_FLAGS``
    value is hashed into the directory name — two processes with different
    codegen flags can never reload each other's executables. The
    ``JAX_PERSISTENT_CACHE_*`` env knobs are honored when set. The cache is a
    pure optimization: any failure to set it up is reported and skipped.
    """
    cache_dir = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir is None:
        flags = os.environ.get("XLA_FLAGS", "")
        suffix = ("-" + hashlib.sha256(flags.encode()).hexdigest()[:12]
                  if flags else "")
        cache_dir = _DEFAULT_DIR + suffix
    try:
        # Parse everything before the first config.update so the settings
        # apply all-or-nothing (a late parse error must not leave the cache
        # half-enabled while we report it disabled).
        min_secs = float(
            os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", 1.0))
        min_bytes = int(
            os.environ.get("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", 0))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", min_bytes)
    except Exception as e:  # cache must never take an entry point down
        print(f"persistent compile cache disabled ({type(e).__name__}: {e})",
              file=sys.stderr)
        return None
    return cache_dir
