"""Deterministic-CPU platform pinning shared by the analysis drivers.

``tools/jaxcheck.py``, ``tools/quality_gate.py`` and ``p2p-tpu check
--static`` must all see the SAME platform — the deterministic CPU backend
with a virtual multi-device mesh — or their verdicts diverge (a one-device
run degrades the shardcheck dp sweep to dp=1, where every replica group is
degenerate and a real hidden all-gather at dp >= 2 passes unseen). One
helper instead of a copy-pasted env block per driver, so the forcing logic
can only drift in one place.

jax-free by design: this must run before the first backend init (ideally
before ``import jax``; in an already-imported interpreter the caller still
needs ``jax.config.update("jax_platforms", "cpu")`` — see
tests/conftest.py for why env vars alone are too late there).
"""

from __future__ import annotations

import os

#: The virtual CPU device count every analysis driver (and the test
#: conftest) forces: enough for the dp ∈ {1, 2, 4} shardcheck sweep and
#: the dp=4 mesh-parity drills.
VIRTUAL_DEVICES = 8


def force_cpu_platform(virtual_devices: int = VIRTUAL_DEVICES) -> None:
    """Pin the deterministic CPU backend and (unless the operator already
    pinned a count) the virtual multi-device platform via env vars. An
    operator-set ``xla_force_host_platform_device_count`` in ``XLA_FLAGS``
    is respected verbatim."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count"
            f"={virtual_devices}").strip()
