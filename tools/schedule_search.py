"""schedule_search — rehearsal-scale search for per-site reuse schedules.

Finds the fastest ``engine.reuse`` schedule that stays inside the golden
drift budget (ISSUE 15): a greedy per-site relaxation seeded by per-site
cost shares (perfscope's ``--sites`` table when given, else the analytic
per-site FLOP model — the same arithmetic the cost observatory's roofline
uses) and pruned by predicted saving, so compile time goes to the moves
that can actually pay.

    python tools/schedule_search.py                      # default search
    python tools/schedule_search.py --out tools/schedules/default_v1.json
    python tools/schedule_search.py --sites-json sites.json  # measured seed

The workload is the standard rehearsal replace-edit (the same trajectory
tests/test_phase_cache.py pins: 2-prompt edit, STEPS-step DDIM, seeded
latents) at ``--groups`` vmapped groups; drift is the latent MSE against
the in-session UNGATED baseline — the exact quantity the ≤1e-2 golden
budget bounds (quality_gate's ``schedule`` leg re-validates the committed
artifact against the same budget).

Search space (coarse by design — each distinct schedule is one XLA
compile):

1. CFG boundary sweep: ``cfg_gate`` over ``--gate-grid`` (kept at the
   first fraction whose drift fits — the PR-1 operating point).
2. Kind-level flip sweep: one shared reuse fraction for ALL self sites
   (A-SDM feature inheritance), then ALL cross sites earlier than the
   gate (TAD per-block redundancy), each descending ``--grid`` while the
   budget holds and wall time improves.
3. Per-site refinement: sites ordered by cost share (descending), each
   offered one-notch-earlier moves; accepted only if drift stays inside
   budget AND measured time does not regress. ``--prune`` skips sites
   whose predicted saving (share × steps saved) is below the threshold.

The emitted artifact records the measured speedup/drift and carries
``"*"`` defaults alongside the per-site entries, so one artifact serves
models whose layouts have different site counts (unknown site names are
inapplicable-by-design at resolve time).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from p2p_tpu.utils.platform import force_cpu_platform  # noqa: E402

force_cpu_platform()

from p2p_tpu.utils.cache import default_cache_dir  # noqa: E402

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      default_cache_dir(hash_xla_flags=False))


def site_cost_shares(layout, batch: int, seq: int = None) -> dict:
    """Analytic per-site cost share of one U-Net step — the roofline-model
    seed when no measured perfscope ``--sites`` table is given. Per
    attention site: q/k/v/out projections + the two attention matmuls,
    in FLOPs (2·m·n·k per matmul), normalized to sum 1 over all sites.
    The measured table (``tools/perfscope.py --sites``) uses the same
    site names, so the two seeds are interchangeable."""
    from p2p_tpu.engine.reuse import site_name

    shares = {}
    for m in layout.metas:
        p, c, k = m.pixels, m.channels, m.key_len
        # to_q: P×C×C; to_k/to_v: K×Cc×C (Cc unknown here — use C, the
        # share ordering is what matters); to_out: P×C×C; QKᵀ: P×K×C;
        # probs·V: P×K×C.
        flops = 2 * (p * c * c + 2 * k * c * c + p * c * c
                     + 2 * p * k * c)
        shares[site_name(m)] = float(flops * batch)
    total = sum(shares.values()) or 1.0
    return {k: v / total for k, v in shares.items()}


def standard_workload(pipe, steps: int, groups: int):
    """The rehearsal replace-edit workload: (ctx, lats, ctrls) for a
    ``groups``-wide sweep — the same trajectory family the phase-gate
    golden pins."""
    import jax
    import jax.numpy as jnp

    from p2p_tpu.controllers import factory
    from p2p_tpu.engine.sampler import encode_prompts
    from p2p_tpu.parallel import seed_latents

    prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]
    ctrl = factory.attention_replace(
        prompts, steps, cross_replace_steps=0.4, self_replace_steps=0.25,
        tokenizer=pipe.tokenizer, self_max_pixels=8 * 8,
        max_len=pipe.config.text.max_length)
    ctrls = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (groups,) + x.shape), ctrl)
    cond = encode_prompts(pipe, prompts)
    uncond = encode_prompts(pipe, [""] * len(prompts))
    ctx = jnp.concatenate([uncond, cond], axis=0)
    ctx = jnp.broadcast_to(ctx[None], (groups,) + ctx.shape)
    lats = seed_latents(jax.random.PRNGKey(42), groups, len(prompts),
                        pipe.latent_shape)
    return ctx, lats, ctrls, ctrl


class Evaluator:
    """Compile-and-measure one schedule spec on the standard workload.
    Counts evaluations (the search's cost unit) and memoizes by resolved
    table so grid moves that collapse to an already-measured schedule are
    free."""

    def __init__(self, pipe, steps: int, groups: int, reps: int = 3):
        import numpy as np

        self.pipe, self.steps, self.reps = pipe, steps, reps
        self.ctx, self.lats, self.ctrls, self.ctrl = standard_workload(
            pipe, steps, groups)
        self.evals = 0
        self._memo = {}
        base_lat, self.base_s = self._run_timed(None)
        self.base_lat = np.asarray(base_lat, np.float64)

    def _run_timed(self, spec):
        import jax

        from p2p_tpu.parallel.sweep import sweep

        def run():
            _, lat = sweep(self.pipe, self.ctx, self.lats, self.ctrls,
                           num_steps=self.steps, schedule=spec)
            jax.block_until_ready(lat)
            return lat

        lat = run()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(self.reps):
            run()
        return lat, (time.perf_counter() - t0) / self.reps

    def measure(self, spec) -> dict:
        """{'time_s', 'speedup', 'mse'} for one spec (memoized on the
        RESOLVED table — fraction/step spellings that coincide are one
        compile)."""
        import numpy as np

        from p2p_tpu.engine.reuse import resolve_schedule
        from p2p_tpu.models.config import unet_layout

        layout = unet_layout(self.pipe.config.unet)
        key = resolve_schedule(spec, layout, self._scan_steps(),
                               self.ctrl).key()
        if key in self._memo:
            return self._memo[key]
        self.evals += 1
        lat, t = self._run_timed(spec)
        mse = float(((np.asarray(lat, np.float64) - self.base_lat) ** 2)
                    .mean())
        out = {"time_s": t, "speedup": self.base_s / t, "mse": mse}
        self._memo[key] = out
        return out

    def _scan_steps(self) -> int:
        from p2p_tpu.ops import schedulers as sched_mod

        sched = sched_mod.schedule_from_config(
            self.steps, self.pipe.config.scheduler, kind="ddim")
        return int(sched.timesteps.shape[0])


def greedy_search(ev: Evaluator, layout, *, budget: float,
                  gate_grid, grid, prune: float, max_evals: int,
                  sites_shares: dict = None, log=print,
                  margin: float = 0.8) -> dict:
    """The search proper; returns {'spec', 'result', 'trail'}.

    ``margin``: schedules are accepted only under ``margin × budget`` —
    the committed artifact is re-validated against the FULL budget on
    every CI run, and a winner sitting 1% under it would make that leg a
    coin flip on any numeric-platform drift. The headroom is the
    search's, the budget is the gate's."""
    import warnings

    from p2p_tpu.engine.reuse import site_names

    shares = sites_shares or site_cost_shares(layout,
                                              batch=ev.ctx.shape[1])
    cross = list(site_names(layout, "cross"))
    selfs = list(site_names(layout, "self"))
    trail = []

    def try_spec(spec, label):
        if ev.evals >= max_evals:
            return None
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            r = ev.measure(spec)
        ok = r["mse"] <= margin * budget
        log(f"  {label:44s} speedup={r['speedup']:.3f} "
            f"mse={r['mse']:.2e} {'ok' if ok else 'OVER BUDGET'}")
        trail.append({"label": label, **r, "within_budget": ok})
        return r if ok else None

    # 1. CFG boundary: the coarsest, highest-leverage knob. A bare
    # cfg_gate IS the uniform gate (cross sites default to the boundary,
    # self sites to never).
    best_spec = {"cfg_gate": gate_grid[0]}
    best = try_spec(best_spec, f"uniform gate {gate_grid[0]}")
    if best is None:
        raise SystemExit(
            f"uniform gate {gate_grid[0]} already exceeds the drift "
            f"budget {budget} — no schedule can pass; raise --steps or "
            "the budget")
    for g in gate_grid[1:]:
        spec = {**best_spec, "cfg_gate": g}
        r = try_spec(spec, f"uniform gate {g}")
        if r is not None and r["speedup"] > best["speedup"]:
            best_spec, best = spec, r

    # 2. Kind-level flips: all self sites (A-SDM inheritance), then all
    # cross sites earlier than the boundary (TAD).
    for kind in ("self", "cross"):
        for frac in grid:
            spec = {**best_spec, kind: {"*": frac}}
            r = try_spec(spec, f"all-{kind} reuse @{frac}")
            if r is None:
                break   # drift grows monotonically down the grid
            if r["speedup"] >= best["speedup"]:
                best_spec, best = spec, r

    # 3. Per-site refinement, biggest predicted saving first; prune the
    # tail whose share can't pay for its compile.
    ordered = sorted(cross + selfs, key=lambda s: -shares.get(s, 0.0))
    for name in ordered:
        share = shares.get(name, 0.0)
        if share < prune:
            log(f"  pruned {name} (share {share:.3f} < {prune})")
            continue
        kind = "cross" if name.startswith("cross_attn/") else "self"
        table = dict(best_spec.get(kind) or {})
        current = table.get(name, table.get("*"))
        for frac in grid:
            if current is not None and frac >= current:
                continue
            spec = {**best_spec, kind: {**table, name: frac}}
            r = try_spec(spec, f"{name} @{frac}")
            if r is None or r["speedup"] < best["speedup"]:
                break
            best_spec, best = spec, r
            table = dict(best_spec[kind])
            current = frac
        if ev.evals >= max_evals:
            log(f"  eval budget {max_evals} reached")
            break

    return {"spec": best_spec, "result": best, "trail": trail}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=8,
                    help="rehearsal scan length (default 8, the "
                         "phase-gate golden's)")
    ap.add_argument("--groups", type=int, default=4,
                    help="vmapped edit groups in the timed sweep")
    ap.add_argument("--drift-budget", type=float, default=1e-2,
                    help="max latent MSE vs the ungated baseline (the "
                         "golden budget)")
    ap.add_argument("--gate-grid", default="0.5",
                    help="cfg_gate candidate fractions, best-first")
    ap.add_argument("--grid", default="0.75,0.62,0.5,0.44,0.38,0.31,0.25",
                    help="reuse-step candidate fractions, latest-first")
    ap.add_argument("--prune", type=float, default=0.01,
                    help="skip per-site refinement of sites whose "
                         "predicted cost share is below this")
    ap.add_argument("--margin", type=float, default=0.8,
                    help="accept only schedules under margin*budget — "
                         "headroom for the CI leg that re-validates the "
                         "artifact at the full budget")
    ap.add_argument("--max-evals", type=int, default=60,
                    help="hard cap on schedule compilations")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions per measurement")
    ap.add_argument("--sites-json", default=None, metavar="FILE",
                    help="measured per-site share table (the JSON "
                         "tools/perfscope.py --sites emits) to seed the "
                         "refinement order instead of the analytic model")
    ap.add_argument("--profile", default=None, metavar="LEDGER",
                    help="seed the refinement order from a serve "
                         "--profile WorkloadProfile ledger's measured "
                         "per-site shares (ISSUE 18: the engine-captured "
                         "equivalent of --sites-json — no hand-collected "
                         "trace). Mutually exclusive with --sites-json")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the winning schedule artifact here")
    ap.add_argument("--preset", default="tiny",
                    help="model preset (tiny = the CI rehearsal scale)")
    args = ap.parse_args(argv)

    gate_grid = [float(x) for x in args.gate_grid.split(",") if x]
    grid = [float(x) for x in args.grid.split(",") if x]

    from p2p_tpu.models.config import PRESET_CONFIGS, unet_layout
    from tests.test_golden import _pipe

    cfg = PRESET_CONFIGS[args.preset]
    pipe = _pipe(cfg)
    layout = unet_layout(cfg.unet)

    if args.profile and args.sites_json:
        ap.error("--profile and --sites-json both seed the measured "
                 "share table — pass one")
    shares = None
    shares_src = None
    if args.sites_json:
        with open(args.sites_json) as f:
            data = json.load(f)
        shares = {e["site"]: e["share"] for e in data["sites"]}
        shares_src = args.sites_json
    elif args.profile:
        from p2p_tpu.obs import traceparse

        try:
            doc = traceparse.load_workload_profile(args.profile)
            entries = traceparse.profile_sites(doc)
        except (OSError, ValueError) as e:
            print(f"--profile: {e}", file=sys.stderr)
            return 2
        shares = {e["site"]: e["share"] for e in entries}
        shares_src = args.profile
    if shares is not None:
        print(f"seeded by measured shares: {shares_src} "
              f"({len(shares)} sites)")

    print(f"baseline: ungated {args.steps}-step replace edit, "
          f"{args.groups} groups")
    ev = Evaluator(pipe, args.steps, args.groups, reps=args.reps)
    print(f"  ungated {ev.base_s:.3f}s/run; searching "
          f"(budget mse<={args.drift_budget}, <= {args.max_evals} evals)")
    found = greedy_search(ev, layout, budget=args.drift_budget,
                          gate_grid=gate_grid, grid=grid, prune=args.prune,
                          max_evals=args.max_evals, sites_shares=shares,
                          margin=args.margin)

    r = found["result"]
    uniform = found["trail"][0]
    print(f"winner: speedup {r['speedup']:.3f}x (uniform gate "
          f"{uniform['speedup']:.3f}x), mse {r['mse']:.2e}, "
          f"{ev.evals} compile(s)")
    if args.out:
        spec = dict(found["spec"])
        spec["version"] = 1
        spec["provenance"] = {
            "tool": "tools/schedule_search.py",
            "preset": args.preset,
            "steps": args.steps,
            "groups": args.groups,
            "drift_budget": args.drift_budget,
            "measured_speedup": round(r["speedup"], 4),
            "uniform_gate_speedup": round(uniform["speedup"], 4),
            "measured_mse": r["mse"],
            "evals": ev.evals,
        }
        if shares_src is not None:
            spec["provenance"]["sites_source"] = shares_src
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(spec, f, indent=2)
            f.write("\n")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
