"""Real-weights parity harness: one command, per-stage max-abs report.

    python tools/parity_real_weights.py /path/to/stable-diffusion-v1-4 \
        --preset sd14 --steps 3 --out-dir parity_out/

Loads a diffusers-format checkpoint directory into OUR pipeline
(`p2p_tpu.models.checkpoint.load_pipeline` — the path the reference gets
from `StableDiffusionPipeline.from_pretrained`, `/root/reference/main.py:29`)
and runs the BASELINE config-1 AttentionReplace edit twice: once through our
jitted `text2image`, once through the independent hand-rolled torch
reference loop the e2e parity suite maintains
(`tests/test_e2e_parity_torch.py`, spec
`/root/reference/ptp_utils.py:65-76,129-172` + `main.py:85-98,162-230`).
Writes both images plus `report.json` with a per-stage max-abs breakdown:

    text_encoder   last_hidden_state, ours vs torch tower
    unet_eps       one CFG U-Net forward at the first timestep
    loop_latent    final latent after the full controlled sampling loop
    vae_decode     the torch loop's final latent decoded through both VAEs
                   (f32 image — isolates the decoder from loop drift)
    image          final uint8 images (max + mean pixel diff)

Exit 0 iff the uint8 images agree within one quantization level — the
"pixel-matching the PyTorch reference" criterion (BASELINE.json:5). No
pretrained weights ship in this repo; the harness is exercised end-to-end
against an HF-format random-weight checkpoint by
`tests/test_parity_harness.py`, so the day real weights are available this
is a 5-minute check (docs/CHECKPOINTS.md §"Real-weights parity").
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def main(argv=None):
    from p2p_tpu.models.config import PRESET_CONFIGS

    ap = argparse.ArgumentParser(
        description="Per-stage parity of a real checkpoint vs the torch "
                    "reference loop")
    ap.add_argument("checkpoint", help="diffusers-format checkpoint dir")
    ap.add_argument("--preset", default="sd14", choices=tuple(PRESET_CONFIGS))
    ap.add_argument("--prompts", nargs=2,
                    default=["a squirrel eating a burger",
                             "a squirrel eating a lasagna"],
                    help="source and edit prompt (same word count: Replace)")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--guidance", type=float, default=None,
                    help="default: the preset's guidance scale")
    ap.add_argument("--cross-replace", type=float, default=0.8)
    ap.add_argument("--self-replace", type=float, default=0.4)
    ap.add_argument("--out-dir", default="parity_out")
    ap.add_argument("--dpm-operating-point", action="store_true",
                    help="also render DDIM-50 vs DPM-20 from the same x_T "
                         "through our pipeline (side-by-side PNGs + PSNR) — "
                         "the image-level leg of PERF.md's quality-matched "
                         "operating point, meaningful on trained weights")
    ap.add_argument("--device", choices=("cpu", "default"), default="cpu",
                    help="cpu (default): force the jax CPU backend so both "
                         "sides run f32 on the same hardware; 'default' "
                         "keeps the ambient backend (expect bf16-scale "
                         "drift on TPU)")
    args = ap.parse_args(argv)

    import jax

    if args.device == "cpu":
        # Works even when sitecustomize already imported jax (the backend
        # initializes lazily; see .claude/skills/verify/SKILL.md).
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import jax.numpy as jnp
    from PIL import Image

    from p2p_tpu.controllers import factory
    from p2p_tpu.models.checkpoint import load_pipeline
    from p2p_tpu.models.unet import apply_unet
    from p2p_tpu.models import vae as vae_mod
    from p2p_tpu.ops import schedulers as sched_mod
    from p2p_tpu.utils.tokenizer import pad_ids

    # The independent torch reference loop the e2e suite maintains.
    import test_e2e_parity_torch as O
    torch = O.torch

    cfg = PRESET_CONFIGS[args.preset]
    guidance = cfg.guidance_scale if args.guidance is None else args.guidance
    prompts = list(args.prompts)
    steps = args.steps
    L = cfg.unet.context_len
    vpred = cfg.scheduler.prediction_type == "v_prediction"

    print(f"loading {args.checkpoint} as {cfg.name} ...", flush=True)
    pipe = load_pipeline(args.checkpoint, cfg)
    tok = pipe.tokenizer

    report = {"checkpoint": os.path.abspath(args.checkpoint),
              "preset": args.preset, "prompts": prompts, "steps": steps,
              "guidance": guidance, "seed": args.seed, "stages": {}}

    def stage(name, ours, theirs, note=""):
        d = float(np.max(np.abs(np.asarray(ours, np.float32)
                                - np.asarray(theirs, np.float32))))
        report["stages"][name] = {"max_abs": d, **({"note": note} if note else {})}
        print(f"  [{name}] max|ours - torch| = {d:.3e} {note}", flush=True)
        return d

    # --- stage 1: text encoder -------------------------------------------
    from p2p_tpu.engine.sampler import encode_prompts

    all_prompts = prompts + [""] * len(prompts)
    ours_enc = encode_prompts(pipe, all_prompts)
    if cfg.text.arch == "ldmbert":
        pad = getattr(tok, "pad_token_id", tok.eos_token_id)
        ids = np.asarray([pad_ids(tok.encode(p), L, pad) for p in all_prompts],
                         dtype=np.int64)
        with torch.no_grad():
            torch_enc = O._torch_text_oracle(pipe.text_params, cfg.text, ids)
    else:
        torch_enc = O._torch_text_encode(cfg, pipe.text_params, tok,
                                         all_prompts)
    stage("text_encoder", ours_enc, torch_enc.numpy())

    # --- shared latent + contexts ----------------------------------------
    x_t = jax.random.normal(jax.random.PRNGKey(args.seed),
                            (1,) + pipe.latent_shape, jnp.float32)
    n = len(prompts)
    ctx_torch = torch.cat([torch_enc[n:], torch_enc[:n]], dim=0)

    # --- stage 2: one CFG U-Net forward at the first timestep ------------
    schedule = sched_mod.schedule_from_config(steps, cfg.scheduler,
                                              kind="ddim")
    t0 = int(np.asarray(schedule.timesteps)[0])
    lat_b = jnp.broadcast_to(x_t, (2 * n,) + x_t.shape[1:])
    ours_eps, _ = apply_unet(
        pipe.unet_params, cfg.unet, lat_b, jnp.int32(t0),
        jnp.concatenate([ours_enc[n:], ours_enc[:n]], axis=0))
    lat_t = O._to_t(np.asarray(x_t)).permute(0, 3, 1, 2).expand(
        2 * n, -1, -1, -1)
    with torch.no_grad():
        torch_eps = O._torch_unet(pipe.unet_params, cfg.unet, lat_t, t0,
                                  ctx_torch, None)
    stage("unet_eps", ours_eps,
          torch_eps.permute(0, 2, 3, 1).numpy())

    # --- stage 3+5: the full controlled loop -----------------------------
    # Ours rides the dp sweep engine at G=1 — the same `_denoise_scan`
    # program `text2image` compiles (pinned equal by tests/test_parallel.py)
    # but returning the final latents the loop_latent stage needs.
    from p2p_tpu.parallel import sweep

    controller = factory.attention_replace(
        prompts, steps, cross_replace_steps=args.cross_replace,
        self_replace_steps=args.self_replace, tokenizer=tok,
        self_max_pixels=O.SELF_MAX_PIXELS, max_len=L)
    ctrls = jax.tree_util.tree_map(lambda a: a[None], controller)
    ctx_ours = jnp.concatenate([ours_enc[n:], ours_enc[:n]], axis=0)
    lats0 = jnp.broadcast_to(x_t, (n,) + x_t.shape[1:])
    ours_imgs, ours_final = sweep(pipe, ctx_ours[None], lats0[None], ctrls,
                                  num_steps=steps, guidance_scale=guidance,
                                  scheduler="ddim")
    ours_img = np.asarray(ours_imgs[0])
    ours_final = np.asarray(ours_final[0])

    # Edit precompute: the reference's own host-side functions when the
    # checkout is present, else our parity-pinned equivalents.
    mapper = cross_alpha = None
    if os.path.isdir(O.REFERENCE_DIR):
        sys.path.insert(0, O.REFERENCE_DIR)
        try:
            import ptp_utils as ref_ptp
            import seq_aligner as ref_aligner

            m = ref_aligner.get_replacement_mapper(
                prompts, tok, max_len=L).float()
            a = ref_ptp.get_time_words_attention_alpha(
                prompts, steps, args.cross_replace, tok,
                max_num_words=L).float()
            mapper, cross_alpha = m, a  # atomic: both or fall back to ours
            report["edit_precompute"] = "reference"
        except Exception as e:
            print(f"  (reference precompute unavailable: {e})", flush=True)
        finally:
            sys.path.remove(O.REFERENCE_DIR)
    if mapper is None:
        from p2p_tpu.align.aligner import get_replacement_mapper
        from p2p_tpu.align.words import get_time_words_attention_alpha

        mapper = torch.from_numpy(np.asarray(
            get_replacement_mapper(prompts, tok, max_len=L), np.float32))
        cross_alpha = torch.from_numpy(np.asarray(
            get_time_words_attention_alpha(
                prompts, steps, args.cross_replace, tok, max_num_words=L),
            np.float32))
        report["edit_precompute"] = "p2p_tpu.align (reference unavailable)"

    make_hook = O._make_edit_hook(
        "replace", mapper, cross_alpha,
        self_window=(0, int(steps * args.self_replace)))

    final_lat = {}

    def capture_post_step(step, latents):
        # Runs after the helper's own (unduplicated) DDIM update.
        final_lat["lat"] = latents
        return latents

    torch_img = O._torch_cfg_sample(
        pipe, cfg, ctx_torch, x_t, n, make_hook, guidance, steps,
        vpred=vpred, post_step=capture_post_step)

    torch_final = final_lat["lat"]
    stage("loop_latent", ours_final,
          torch_final.permute(0, 2, 3, 1).numpy(),
          note=f"(after {steps} controlled CFG steps)")

    # --- stage 4: VAE decode of the torch loop's final latent through both
    ours_dec = vae_mod.decode(
        pipe.vae_params, cfg.vae,
        jnp.asarray(torch_final.permute(0, 2, 3, 1).numpy()))
    with torch.no_grad():
        torch_dec = O._torch_vae_decode(pipe.vae_params, cfg.vae, torch_final)
    stage("vae_decode", ours_dec,
          torch_dec.permute(0, 2, 3, 1).numpy(),
          note="(f32 image in [-1,1], shared input latent)")

    # --- stage 5: final images -------------------------------------------
    diff = np.abs(ours_img.astype(np.int32) - torch_img.astype(np.int32))
    report["stages"]["image"] = {"max_abs": int(diff.max()),
                                 "mean_abs": float(diff.mean())}
    print(f"  [image] max pixel diff = {diff.max()}, "
          f"mean = {diff.mean():.5f}", flush=True)

    os.makedirs(args.out_dir, exist_ok=True)
    for i in range(n):
        Image.fromarray(ours_img[i]).save(
            os.path.join(args.out_dir, f"ours_{i}.png"))
        Image.fromarray(torch_img[i]).save(
            os.path.join(args.out_dir, f"torch_ref_{i}.png"))

    if args.dpm_operating_point:
        # Image-level check of PERF.md's quality-matched operating point
        # (DPM-Solver++(2M) @ 20 steps ≈ DDIM @ 50): same x_T, both solvers
        # through OUR pipeline, side-by-side PNGs + PSNR between them. On
        # random weights the ε-field is not smooth in λ so the numbers are
        # meaningless (tests/test_dpm_quality.py pins why); on real weights
        # this is the missing image-level leg of that argument.
        from p2p_tpu.engine.sampler import text2image

        ddim_steps, dpm_steps = ((4, 2) if args.preset
                                 in ("tiny", "tiny_ldm") else (50, 20))
        pair = {}
        for kind, ksteps in (("ddim", ddim_steps), ("dpm", dpm_steps)):
            kimg, _, _ = text2image(pipe, prompts[:1], None,
                                    num_steps=ksteps, scheduler=kind,
                                    guidance_scale=guidance, latent=x_t)
            pair[kind] = np.asarray(kimg[0])
            Image.fromarray(pair[kind]).save(os.path.join(
                args.out_dir, f"quality_{kind}{ksteps}.png"))
        mse = float(np.mean((pair["ddim"].astype(np.float32)
                             - pair["dpm"].astype(np.float32)) ** 2))
        psnr = float("inf") if mse == 0 else 10 * np.log10(255.0 ** 2 / mse)
        report["dpm_operating_point"] = {
            "ddim_steps": ddim_steps, "dpm_steps": dpm_steps,
            "psnr_db": round(psnr, 2),
            "note": "image-level leg of PERF.md's DPM-20≈DDIM-50 claim; "
                    "only meaningful on trained weights"}
        print(f"  [dpm_operating_point] DDIM-{ddim_steps} vs DPM-{dpm_steps}"
              f" PSNR = {psnr:.2f} dB", flush=True)

    ok = diff.max() <= 1
    report["pass"] = bool(ok)
    with open(os.path.join(args.out_dir, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(f"report + images written to {args.out_dir}/", flush=True)
    print("PARITY PASS" if ok else "PARITY FAIL (max pixel diff > 1)",
          flush=True)
    return 0 if ok else 2


if __name__ == "__main__":
    sys.exit(main())
