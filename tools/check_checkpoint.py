"""Checkpoint-readiness report — thin wrapper over
`p2p_tpu.models.checkpoint_check` (also exposed as `p2p-tpu check`).

    python tools/check_checkpoint.py /path/to/sd14-checkpoint --preset sd14
"""

import sys

from p2p_tpu.models.checkpoint_check import main

if __name__ == "__main__":
    sys.exit(main())
