"""Long-horizon lifecycle soak: hours-equivalent virtual-clock traffic
through repeated snapshot / compact / drain / restart cycles, with hard
resource invariants asserted at every cycle boundary.

The chaos drill proves the lifecycle machinery is *correct* (exactly-once,
bitwise, snapshot+tail ≡ full history); this drill proves it is
*durable*: a server that drains, snapshots and warm-restarts many times
over a long horizon must not slowly rot. One streaming loadgen trace
(``generate_stream`` — never materialized) is re-fed to every incarnation;
the journal dedupes what earlier cycles already served, each cycle drains
after its share of new terminals, snapshots + compacts, and the next
incarnation warm-restarts from snapshot + WAL tail. Asserted per cycle:

- **exactly-once** — no request id ever reaches two non-``rejected``
  terminals across the whole soak (draining rejections are backpressure
  and may repeat), and every generated request is eventually served;
- **bounded disk** — WAL + carry-spill bytes at each cycle boundary stay
  under a constant, *not* monotone in requests served (compaction + the
  orphan sweep are what make this true);
- **bounded restart cost** — every warm restart replays only the WAL tail
  (a handful of records), never the cumulative history;
- **no resource leaks** — RSS growth across the soak stays under a
  budget; the open-fd count and thread count end where they started;
- **metrics/flight invariants** — every flight record the tracer closes
  is an ``ok`` with ``attribution_ok`` (stage segments tile the whole
  virtual-clock lifetime), each summary's counts reconcile with the
  records seen, and every cycle actually snapshotted.

Fake runners by default (the lifecycle machinery is runner-agnostic and
the point is volume: hundreds of requests, many cycles, seconds of wall
clock); phase-1 runners return carries shaped exactly like the request's
pinned ``carry_template`` so hand-off spills round-trip and mid-drain
pending work genuinely resumes in phase 2 after a restart. ``--real``
swaps in the real compiled runners for a slow full-fidelity pass.

    python tools/soak.py                          # rehearsal defaults
    python tools/soak.py --duration-ms 60000 --rate 20 --cycles 8
    python tools/soak.py --json soak.json         # machine-readable report

Wired into tools/quality_gate.py as the opt-in ``--only soak`` lane.
"""

from __future__ import annotations

import argparse
import gc
import json
import math
import os
import shutil
import sys
import tempfile
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


class SoakFailure(AssertionError):
    """A durability invariant broke during the soak."""


def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"p2p_{name}", os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Resource probes (Linux /proc; None-safe elsewhere)
# ---------------------------------------------------------------------------


def rss_kb():
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return None


def open_fds():
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def disk_bytes(journal_path: str) -> int:
    """WAL + rotated segment + carry spills — the footprint the soak
    bounds (the snapshot is reported separately: its dedupe map grows
    with total ids by design, documented in docs/SERVING.md)."""
    total = 0
    for p in (journal_path, journal_path + ".old"):
        if os.path.exists(p):
            total += os.path.getsize(p)
    carry = journal_path + ".carry"
    if os.path.isdir(carry):
        for name in os.listdir(carry):
            total += os.path.getsize(os.path.join(carry, name))
    return total


# ---------------------------------------------------------------------------
# Fake runners: virtual-clock costs, template-shaped carries
# ---------------------------------------------------------------------------


class _VirtualTimer:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt_s):
        self.t += dt_s


def _fake_factory(pipe, timer):
    """Pool-aware fake runners. Phase-1 runners return carries stacked
    from the request's real ``carry_template``, so spills validate against
    the pinned spec and a restarted incarnation resumes drained hand-offs
    in phase 2 — the full durability path, no U-Net required."""
    import numpy as np

    from p2p_tpu.serve.handoff import carry_template

    templates: dict = {}

    class Runner:
        def __init__(self, key, bucket):
            self.key, self.bucket = key, bucket
            self.tag = key[0] if key else None

        def warm(self, entries):
            timer.advance(0.05)

        def __call__(self, entries, guidance):
            if self.tag == "phase1":
                import jax

                timer.advance(0.02)
                prep = entries[0].prepared
                tkey = prep.phase2_key
                if tkey not in templates:
                    templates[tkey] = jax.tree_util.tree_map(
                        np.asarray, carry_template(pipe, prep))
                return jax.tree_util.tree_map(
                    lambda x: np.broadcast_to(
                        x[None], (self.bucket,) + x.shape).copy(),
                    templates[tkey])
            if self.tag == "phase2":
                for e in entries:
                    assert e.carry is not None
                timer.advance(0.01)
            else:
                timer.advance(0.03)
            return np.zeros((self.bucket, 2, 2, 2, 3), np.uint8)

    return lambda key, bucket: Runner(key, bucket)


# ---------------------------------------------------------------------------
# The soak
# ---------------------------------------------------------------------------


def run_soak(pipe, *, cycles=6, duration_ms=30000.0, rate_per_s=20.0,
             seed=0, steps=4, gate_mix_spec="0.5:1,off:1",
             snapshot_every_ms=4000.0, drain_timeout_ms=None,
             workdir=None, real=False, rss_budget_mb=256.0,
             min_requests=0, min_cycles=0, progress=print) -> dict:
    """Run the soak; raise :class:`SoakFailure` on any invariant
    violation; return the report dict."""
    import time

    from p2p_tpu.obs.flight import FlightTracer
    from p2p_tpu.serve import Journal, serve_forever
    from p2p_tpu.serve.engine_loop import TERMINAL_STATUSES
    from p2p_tpu.serve.lifecycle import DrainController

    loadgen = _load_tool("loadgen")
    gate_mix = (loadgen.parse_gate_mix(gate_mix_spec)
                if gate_mix_spec else None)

    def stream():
        return loadgen.generate_stream(
            duration_ms, mode="poisson", rate_per_s=rate_per_s, seed=seed,
            steps=steps, gate_mix=gate_mix)

    n_expected = sum(1 for _ in stream())
    quota = max(1, math.ceil(n_expected / cycles))
    owns_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="p2p-soak-")
    os.makedirs(workdir, exist_ok=True)
    journal_path = os.path.join(workdir, "soak.wal")
    for p in (journal_path, journal_path + ".snapshot",
              journal_path + ".old"):
        if os.path.exists(p):
            os.remove(p)
    if os.path.isdir(journal_path + ".carry"):
        shutil.rmtree(journal_path + ".carry")

    timer = _VirtualTimer() if not real else time.perf_counter
    runner_factory = None if real else _fake_factory(pipe, timer)

    resolved: dict = {}
    per_cycle = []
    rss0 = fds0 = threads0 = None
    total_snapshots = 0
    t_wall0 = time.perf_counter()

    for cycle in range(cycles):
        gc.collect()
        if cycle == 0:
            rss0, fds0 = rss_kb(), open_fds()
            threads0 = threading.active_count()
        ctl = DrainController()
        tracer = FlightTracer()
        journal = Journal(journal_path)
        rs = journal.replay_state
        if cycle > 0:
            # Bounded restart: a warm restart reads the snapshot plus a
            # handful of tail records — never the cumulative history.
            if not rs.snapshot_loaded:
                raise SoakFailure(f"cycle {cycle}: restart found no "
                                  f"snapshot to warm-start from")
            if rs.wal_records > 64:
                raise SoakFailure(
                    f"cycle {cycle}: restart replayed {rs.wal_records} "
                    f"WAL tail records — compaction is not bounding "
                    f"restart cost")
        last = cycle == cycles - 1
        count = 0
        summary = None
        for rec in serve_forever(
                pipe, stream(), journal=journal, lifecycle=ctl,
                flight=tracer, snapshot_every_ms=snapshot_every_ms,
                drain_timeout_ms=drain_timeout_ms,
                runner_factory=runner_factory, timer=timer,
                max_batch=4, max_wait_ms=25.0, queue_cap=512,
                phase2_max_batch=4):
            status = rec.get("status")
            if status == "summary":
                summary = rec
                continue
            if status not in TERMINAL_STATUSES or status == "rejected":
                continue
            rid = rec["request_id"]
            if rid in resolved:
                raise SoakFailure(f"request {rid!r} resolved twice "
                                  f"(cycle {resolved[rid]} then {cycle})")
            resolved[rid] = cycle
            count += 1
            if not last and count >= quota and not ctl.requested:
                ctl.request(f"soak cycle {cycle}")
        journal.close()

        # Flight invariants: pure healthy traffic — every closed record
        # must be an attribution-exact ok (draining rejections close no
        # flight record by design).
        for frec in tracer.records:
            if frec["status"] != "ok":
                raise SoakFailure(
                    f"cycle {cycle}: flight record {frec['trace_id']} has "
                    f"status {frec['status']!r} in a fault-free soak")
            if not frec.get("attribution_ok"):
                raise SoakFailure(
                    f"cycle {cycle}: flight record {frec['trace_id']} "
                    f"failed attribution "
                    f"(unattributed {frec['unattributed_ms']}ms)")
        if summary is None:
            raise SoakFailure(f"cycle {cycle}: no summary record")
        if summary["counts"]["ok"] != len(tracer.records):
            raise SoakFailure(
                f"cycle {cycle}: summary says {summary['counts']['ok']} "
                f"ok but the tracer closed {len(tracer.records)} records")
        snaps = summary.get("snapshots", 0)
        if snaps < 1 and summary["counts"]["ok"]:
            # A cycle that served nothing (every id already terminal)
            # dispatches nothing and so never reaches the snapshot point —
            # only cycles that did work must have compacted.
            raise SoakFailure(f"cycle {cycle}: no snapshot taken")
        total_snapshots += snaps

        gc.collect()
        facts = {"cycle": cycle,
                 "served_ok": summary["counts"]["ok"],
                 "snapshots": snaps,
                 "restart_tail_records": rs.wal_records,
                 "orphans_swept": rs.orphans_swept,
                 "resumed_handoffs": summary.get("phases", {}).get(
                     "resumed_handoffs", 0),
                 "disk_bytes": disk_bytes(journal_path),
                 "snapshot_bytes": (os.path.getsize(
                     journal_path + ".snapshot")
                     if os.path.exists(journal_path + ".snapshot") else 0),
                 "rss_kb": rss_kb(),
                 "open_fds": open_fds(),
                 "threads": threading.active_count()}
        per_cycle.append(facts)
        progress(f"soak cycle {cycle}: +{facts['served_ok']} ok "
                 f"({len(resolved)}/{n_expected} total), "
                 f"disk {facts['disk_bytes']}B, "
                 f"rss {facts['rss_kb']}kB, fds {facts['open_fds']}")

    # ------------------------------------------------------------------
    # Whole-soak invariants
    # ------------------------------------------------------------------
    failures = []
    if len(resolved) != n_expected:
        missing = n_expected - len(resolved)
        failures.append(f"{missing} request(s) never served")
    if min_requests and len(resolved) < min_requests:
        failures.append(f"served {len(resolved)} < required "
                        f"{min_requests} requests")
    if min_cycles and cycles < min_cycles:
        failures.append(f"ran {cycles} < required {min_cycles} cycles")

    # Bounded disk: WAL+spill at every cycle boundary under a constant —
    # 64KB or twice the first cycle's footprint, whichever is larger —
    # and in particular NOT monotone in requests served.
    disk = [f["disk_bytes"] for f in per_cycle]
    disk_cap = max(65536, 2 * max(disk[0], 1))
    if max(disk) > disk_cap:
        failures.append(f"WAL+spill disk grew past the bound: {disk} "
                        f"(cap {disk_cap})")

    rss = [f["rss_kb"] for f in per_cycle]
    rss_growth_kb = None
    if rss0 is not None and all(r is not None for r in rss):
        rss_growth_kb = rss[-1] - rss0
        if rss_growth_kb > rss_budget_mb * 1024:
            failures.append(f"RSS grew {rss_growth_kb}kB > budget "
                            f"{rss_budget_mb}MB")
    fds = [f["open_fds"] for f in per_cycle]
    if fds0 is not None and all(f is not None for f in fds):
        if fds[-1] > fds0 + 8:
            failures.append(f"fd leak: {fds0} -> {fds[-1]}")
    threads = [f["threads"] for f in per_cycle]
    if threads[-1] > threads0 + 2:
        failures.append(f"thread leak: {threads0} -> {threads[-1]}")

    report = {"ok": not failures,
              "failures": failures,
              "cycles": cycles,
              "requests_expected": n_expected,
              "requests_served": len(resolved),
              "snapshots_total": total_snapshots,
              "resumed_handoffs_total": sum(
                  f["resumed_handoffs"] for f in per_cycle),
              "disk_bytes_per_cycle": disk,
              "disk_cap_bytes": disk_cap,
              "rss_growth_kb": rss_growth_kb,
              "fds_first_last": [fds0, fds[-1]],
              "threads_first_last": [threads0, threads[-1]],
              "wall_s": round(time.perf_counter() - t_wall0, 2),
              "per_cycle": per_cycle}
    if failures:
        # Leave the workdir in place as evidence.
        raise SoakFailure("; ".join(failures) + f" (workdir: {workdir})")
    if owns_workdir:
        shutil.rmtree(workdir, ignore_errors=True)
    return report


def main(argv=None) -> int:
    chaos_drill = _load_tool("chaos_drill")
    chaos_drill._pin_cpu()
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cycles", type=int, default=6)
    ap.add_argument("--duration-ms", type=float, default=30000.0,
                    help="virtual-clock horizon of the streaming trace")
    ap.add_argument("--rate", type=float, default=20.0,
                    help="poisson arrivals per (virtual) second")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--gate-mix", default="0.5:1,off:1",
                    help="loadgen gate mix ('' = all ungated); gated "
                         "requests exercise the hand-off spill path")
    ap.add_argument("--snapshot-every-ms", type=float, default=4000.0)
    ap.add_argument("--drain-timeout-ms", type=float, default=None,
                    help="drain budget per cycle (virtual ms with the fake "
                         "runners' injected timer): a tight budget leaves "
                         "pending hand-offs behind, so restarts exercise "
                         "the phase-2 resume path (default: 60 with fake "
                         "runners, unbounded with --real)")
    ap.add_argument("--workdir", default=None,
                    help="journal directory (default: a fresh tempdir, "
                         "removed afterwards)")
    ap.add_argument("--real", action="store_true",
                    help="real compiled runners + wall clock instead of "
                         "the fake virtual-clock runners (slow)")
    ap.add_argument("--rss-budget-mb", type=float, default=256.0)
    ap.add_argument("--min-requests", type=int, default=500,
                    help="fail if the horizon produced fewer requests")
    ap.add_argument("--min-cycles", type=int, default=5)
    ap.add_argument("--json", default=None, metavar="FILE",
                    help="also write the report as JSON")
    args = ap.parse_args(argv)

    drain_timeout = args.drain_timeout_ms
    if drain_timeout is None and not args.real:
        drain_timeout = 60.0
    pipe = chaos_drill.tiny_pipeline()
    try:
        report = run_soak(
            pipe, cycles=args.cycles, duration_ms=args.duration_ms,
            rate_per_s=args.rate, seed=args.seed, steps=args.steps,
            gate_mix_spec=args.gate_mix,
            snapshot_every_ms=args.snapshot_every_ms,
            drain_timeout_ms=drain_timeout,
            workdir=args.workdir, real=args.real,
            rss_budget_mb=args.rss_budget_mb,
            min_requests=args.min_requests, min_cycles=args.min_cycles,
            progress=lambda msg: print(msg, file=sys.stderr))
    except SoakFailure as e:
        print(f"SOAK FAILED: {e}", file=sys.stderr)
        return 1
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(report, indent=2, sort_keys=True))
    print(f"soak OK: {report['requests_served']} requests across "
          f"{report['cycles']} snapshot/compact/restart cycles; disk "
          f"bounded at {max(report['disk_bytes_per_cycle'])}B, RSS growth "
          f"{report['rss_growth_kb']}kB", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
