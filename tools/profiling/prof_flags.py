"""XLA flag sweep for the SD14 50-step scan + GN/flash validation.

Run when the TPU lease is healthy (each variant re-runs this script in a
subprocess so XLA_FLAGS take effect at backend init):

    python tools/profiling/prof_flags.py            # sweep driver
    python tools/profiling/prof_flags.py --inner    # one measurement
"""
import os
import subprocess
import sys

VARIANTS = {
    "baseline": "",
    "latency_hiding": "--xla_tpu_enable_latency_hiding_scheduler=true",
    "vmem_128m": "--xla_tpu_scoped_vmem_limit_kib=131072",
    "async_streams": "--xla_tpu_enable_async_collective_fusion=true",
    "latency_vmem": ("--xla_tpu_enable_latency_hiding_scheduler=true "
                     "--xla_tpu_scoped_vmem_limit_kib=131072"),
    # Data-formatting attack (the 11% relayout share in the round-2 trace).
    # Unknown-flag variants fail at backend init in seconds and are reported
    # FAILED by the sweep — they never cost real chip time.
    "sched_features": "--xla_tpu_enable_all_experimental_scheduler_features=true",
    "vmem_192m": "--xla_tpu_scoped_vmem_limit_kib=196608",
    "latency_vmem192": ("--xla_tpu_enable_latency_hiding_scheduler=true "
                        "--xla_tpu_scoped_vmem_limit_kib=196608"),
}


def inner():
    from _bench_common import sd14_scan_ms_per_step

    print(f"RESULT {sd14_scan_ms_per_step():.2f} ms/step", flush=True)


def main():
    if "--inner" in sys.argv:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        inner()
        return
    for name, flags in VARIANTS.items():
        env = dict(os.environ)
        if flags:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flags).strip()
        # Per-variant cache isolation: enable_persistent_cache hashes the
        # variant's XLA_FLAGS into the cache directory name — but only on
        # its default path, so drop any inherited explicit cache dir.
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner"],
                env=env, timeout=900, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True).stdout
        except subprocess.TimeoutExpired:
            print(f"{name:16s}: TIMEOUT", flush=True)
            continue
        line = next((l for l in out.splitlines() if l.startswith("RESULT")), None)
        if line is None:
            tail = "\n    ".join(out.splitlines()[-5:])
            print(f"{name:16s}: FAILED —\n    {tail}", flush=True)
        else:
            print(f"{name:16s}: {line}", flush=True)


if __name__ == "__main__":
    main()
