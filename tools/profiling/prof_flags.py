"""XLA flag sweep for the SD14 50-step scan + GN/flash validation.

Run when the TPU lease is healthy (each variant re-runs this script in a
subprocess so XLA_FLAGS take effect at backend init):

    python tools/profiling/prof_flags.py            # sweep driver
    python tools/profiling/prof_flags.py --inner    # one measurement
"""
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VARIANTS = {
    "baseline": "",
    "latency_hiding": "--xla_tpu_enable_latency_hiding_scheduler=true",
    "vmem_128m": "--xla_tpu_scoped_vmem_limit_kib=131072",
    "async_streams": "--xla_tpu_enable_async_collective_fusion=true",
}


def inner():
    sys.path.insert(0, REPO)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.models import SD14, init_unet, unet_layout
    from p2p_tpu.models.unet import apply_unet

    cfg = SD14
    layout = unet_layout(cfg.unet)
    params = init_unet(jax.random.PRNGKey(0), cfg.unet)
    s = cfg.latent_size
    x = jnp.ones((4, s, s, cfg.unet.in_channels), jnp.bfloat16)
    ctx = jnp.ones((4, cfg.unet.context_len, cfg.unet.context_dim), jnp.bfloat16)

    @jax.jit
    def scan(params, x, ctx):
        def body(h, t):
            eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
            return eps, None
        out, _ = jax.lax.scan(body, x, jnp.arange(50, dtype=jnp.int32))
        return out

    np.asarray(scan(params, x, ctx))
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        np.asarray(scan(params, x, ctx))
        best = min(best, time.perf_counter() - t0)
    print(f"RESULT {best / 50 * 1000:.2f} ms/step", flush=True)


def main():
    if "--inner" in sys.argv:
        inner()
        return
    for name, flags in VARIANTS.items():
        env = dict(os.environ)
        if flags:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flags).strip()
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner"],
                env=env, timeout=900, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True).stdout
        except subprocess.TimeoutExpired:
            print(f"{name:16s}: TIMEOUT", flush=True)
            continue
        line = next((l for l in out.splitlines() if l.startswith("RESULT")), "no result")
        print(f"{name:16s}: {line}", flush=True)


if __name__ == "__main__":
    main()
