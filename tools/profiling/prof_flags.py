"""XLA compiler-option sweep for the SD14 50-step scan.

On the axon platform the local client's XLA_FLAGS parser does not know the
libtpu ``--xla_tpu_*`` flags (the backend compiler runs server-side behind
the PJRT tunnel) — passing them through the environment is a fatal parse
error before backend init. The working route is per-program
``jax.jit(..., compiler_options=...)``, which PJRT forwards to the real TPU
compiler.

Each variant still runs in a subprocess — not for flag isolation (options
are per-compile now) but so a wedged lease or hung compile costs one
TIMEOUT line, not the whole sweep:

    python tools/profiling/prof_flags.py            # sweep driver
    python tools/profiling/prof_flags.py --inner '{"...": "..."}'
"""
import json
import os
import subprocess
import sys
import time

VARIANTS = {
    "baseline": {},
    "latency_hiding": {"xla_tpu_enable_latency_hiding_scheduler": "true"},
    "vmem_128m": {"xla_tpu_scoped_vmem_limit_kib": "131072"},
    "vmem_192m": {"xla_tpu_scoped_vmem_limit_kib": "196608"},
    "latency_vmem128": {"xla_tpu_enable_latency_hiding_scheduler": "true",
                        "xla_tpu_scoped_vmem_limit_kib": "131072"},
    "latency_vmem192": {"xla_tpu_enable_latency_hiding_scheduler": "true",
                        "xla_tpu_scoped_vmem_limit_kib": "196608"},
    # Data-formatting attack (the 11% relayout share in the round-2 trace).
    # Unknown options come back as a catchable compile error and are
    # reported FAILED — they never cost real chip time.
    "sched_features": {
        "xla_tpu_enable_all_experimental_scheduler_features": "true"},
    "latency_sched_vmem192": {
        "xla_tpu_scoped_vmem_limit_kib": "196608",
        "xla_tpu_enable_latency_hiding_scheduler": "true",
        "xla_tpu_enable_all_experimental_scheduler_features": "true"},
}


def inner(opts_json: str):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _bench_common import sd14_scan_ms_per_step

    opts = json.loads(opts_json)
    ms = sd14_scan_ms_per_step(compiler_options=opts or None)
    print(f"RESULT {ms:.2f}", flush=True)


def main():
    if "--inner" in sys.argv:
        i = sys.argv.index("--inner") + 1
        inner(sys.argv[i] if i < len(sys.argv) else "{}")
        return
    results = {}
    for name, opts in VARIANTS.items():
        t0 = time.monotonic()
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner",
                 json.dumps(opts)],
                timeout=900, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True).stdout
        except subprocess.TimeoutExpired:
            print(f"{name:22s}: TIMEOUT", flush=True)
            continue
        line = next((l for l in out.splitlines() if l.startswith("RESULT")),
                    None)
        if line is None:
            tail = "\n    ".join(out.splitlines()[-5:])
            print(f"{name:22s}: FAILED —\n    {tail}", flush=True)
        else:
            results[name] = float(line.split()[1])
            print(f"{name:22s}: {results[name]:.2f} ms/step "
                  f"(wall {time.monotonic() - t0:.0f}s)", flush=True)
    if results:
        best = min(results, key=results.get)
        print(f"BEST {best}: {results[best]:.2f} ms/step", flush=True)


if __name__ == "__main__":
    main()
