"""Scan-unroll probe: does unrolling the 50-step denoise loop help the
server-side scheduler overlap work across steps? Steps are sequentially
dependent, so gains would come from loop-overhead removal and cross-step
fusion of the scheduler math, not real overlap.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _bench_common import sd14_scan_ms_per_step

for unroll in (1, 2, 5):
    ms = sd14_scan_ms_per_step(unroll=unroll)
    print(f"unroll={unroll}: {ms:7.2f} ms/step", flush=True)
