"""On-chip A/B experiments for the SD14 step-time budget.

Default run (round-3 set): baseline scan, gather-vs-broadcast upsample,
flash head-dim pad probe, batch scaling, VAE decode dtype. The round-2
small-site attention lowerings (dot_product_attention everywhere, flash down
to S>=1024) were measured and rejected (+46% step time; PERF.md) — rerun them
with --all.
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

from p2p_tpu.models import SD14, TINY, init_unet, unet_layout
from p2p_tpu.models import vae as vae_mod
from p2p_tpu.models import nn as nn_mod
from p2p_tpu.models.unet import apply_unet
from p2p_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

# P2P_EXP_PRESET=tiny: CPU smoke lane for the experiments themselves (the
# monkeypatched variants must run and stay exact before burning chip time).
cfg = TINY if os.environ.get("P2P_EXP_PRESET") == "tiny" else SD14
if cfg is SD14:
    from _bench_common import require_accelerator
    require_accelerator()
layout = unet_layout(cfg.unet)
params = init_unet(jax.random.PRNGKey(0), cfg.unet)
s = cfg.latent_size

def time_scan(B, label, steps=50):
    x = jnp.ones((B, s, s, cfg.unet.in_channels), jnp.bfloat16)
    ctx = jnp.ones((B, cfg.unet.context_len, cfg.unet.context_dim), jnp.bfloat16)
    @jax.jit
    def scan(params, x, ctx):
        def body(h, t):
            eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
            return eps, None
        out, _ = jax.lax.scan(body, x, jnp.arange(steps, dtype=jnp.int32))
        return out
    t0 = time.perf_counter(); np.asarray(scan(params, x, ctx))
    compile_s = time.perf_counter() - t0
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter(); np.asarray(scan(params, x, ctx))
        best = min(best, time.perf_counter() - t0)
    print(f"{label:28s} B={B:2d}: {best/steps*1000:7.2f} ms/step  "
          f"({B/2 * steps / best / steps:5.2f} img/s-equiv x50step) compile {compile_s:.0f}s",
          flush=True)
    return best / steps

orig_fused = nn_mod.fused_attention
import p2p_tpu.models.unet as unet_mod

# --qkv: re-measure just baseline + the qkv-fused projection A/B (used when
# a window died before 5c, or after a fix to the experiment itself).
qkv_only = "--qkv" in sys.argv

# 1. baseline (current code: broadcast+reshape upsample, einsum f32 probs for
# S<2048, flash for 4096). Same program as _bench_common → warm-cache load.
t_base = time_scan(4, "baseline")

if not qkv_only:
    # 2. old gather-based upsample (pre-round-3) vs the landed
    # broadcast+reshape — quantifies the relayout win on-chip.
    orig_up = nn_mod.upsample_nearest_2x
    def upsample_resize(x):
        b, h, w, c = x.shape
        return jax.image.resize(x, (b, h * 2, w * 2, c), method="nearest")
    nn_mod.upsample_nearest_2x = upsample_resize
    unet_mod.nn.upsample_nearest_2x = upsample_resize
    time_scan(4, "upsample via image.resize")
    nn_mod.upsample_nearest_2x = orig_up
    unet_mod.nn.upsample_nearest_2x = orig_up

    # 3. head_dim pad 40→64 at the flash sites (MXU lane-efficiency probe;
    # semantically exact: zero-padded q/k leave logits unchanged, padded v
    # dims are sliced off). Theory says XLA/Mosaic pad internally and this
    # is a wash — measure to confirm.
    def fused_pad64(q, k, v, scale, mask=None):
        d = q.shape[-1]
        if mask is None and q.shape[-2] == k.shape[-2] and q.shape[-2] >= 2048 and d < 64:
            pad = [(0, 0)] * (q.ndim - 1) + [(0, 64 - d)]
            out = orig_fused(jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad),
                             scale)
            return out[..., :d]
        return orig_fused(q, k, v, scale, mask)
    nn_mod.fused_attention = fused_pad64
    unet_mod.nn.fused_attention = fused_pad64
    time_scan(4, "flash head_dim pad64")
    nn_mod.fused_attention = orig_fused
    unet_mod.nn.fused_attention = orig_fused

    # 4. batch scaling (the bench g-sweep's underlying scan cost).
    for B in (8, 16):
        time_scan(B, "baseline batchscale", steps=25)

    # 5. VAE decode bf16 vs f32
    vparams = vae_mod.init_vae(jax.random.PRNGKey(2), cfg.vae)
    for dt, name in ((jnp.float32, "vae f32"), (jnp.bfloat16, "vae bf16")):
        lat = jnp.ones((2, s, s, cfg.unet.in_channels), dt)
        vdec = jax.jit(lambda p, l: vae_mod.to_uint8(vae_mod.decode(p, cfg.vae, l)))
        np.asarray(vdec(vparams, lat))
        t0 = time.perf_counter(); np.asarray(vdec(vparams, lat))
        print(f"{name}: {(time.perf_counter()-t0)*1000:.0f} ms", flush=True)

    # 5b. head_dim pad 40->128 (full MXU lane width; same exactness argument
    # as pad64 -- measure whether Mosaic's internal padding already covers it).
    def fused_pad128(q, k, v, scale, mask=None):
        d = q.shape[-1]
        if mask is None and q.shape[-2] == k.shape[-2] and q.shape[-2] >= 2048 and d < 128:
            pad = [(0, 0)] * (q.ndim - 1) + [(0, 128 - d)]
            out = orig_fused(jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad),
                             scale)
            return out[..., :d]
        return orig_fused(q, k, v, scale, mask)
    nn_mod.fused_attention = fused_pad128
    unet_mod.nn.fused_attention = fused_pad128
    time_scan(4, "flash head_dim pad128")
    nn_mod.fused_attention = orig_fused
    unet_mod.nn.fused_attention = orig_fused

# 5c. QKV-fused projections: concat the q/k/v kernels inside the forward --
# one (P,C)x(C,3C) MXU op per self site (k/v fused at cross sites) instead
# of three separate dots; the concat is loop-invariant so XLA hoists it out
# of the scan. Exact parity (same weights, split after); identity
# controller only (bit-exact on CPU at TINY scale: same dots, split after).
orig_attn = unet_mod._apply_attention
def attn_fused_qkv(p, x, context, heads, ctx, is_cross):
    meta = ctx.next_meta()
    assert meta.is_cross == is_cross
    assert not unet_mod.controller_touches(ctx.controller, meta), \
        "experiment assumes identity controller"
    b, pix, _ = x.shape
    if is_cross:
        q = nn_mod.linear(p["to_q"], x)
        kv = context @ jnp.concatenate(
            [p["to_k"]["kernel"], p["to_v"]["kernel"]], axis=1
        ).astype(context.dtype)
        k, v = jnp.split(kv, 2, axis=-1)
    else:
        qkv = x @ jnp.concatenate(
            [p["to_q"]["kernel"], p["to_k"]["kernel"], p["to_v"]["kernel"]],
            axis=1).astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
    d_head = q.shape[-1] // heads
    scale = d_head ** -0.5
    def split_heads(t):
        return t.reshape(b, t.shape[1], heads, d_head).transpose(0, 2, 1, 3)
    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    out = nn_mod.fused_attention(q, k, v, scale)
    out = out.transpose(0, 2, 1, 3).reshape(b, pix, heads * d_head)
    return nn_mod.linear(p["to_out"], out)
def _one_forward():
    x = jnp.ones((2, s, s, cfg.unet.in_channels), jnp.bfloat16)
    ctx = jnp.ones((2, cfg.unet.context_len, cfg.unet.context_dim), jnp.bfloat16)
    eps, _ = jax.jit(lambda p, x, c: apply_unet(
        p, cfg.unet, x, jnp.int32(0), c, layout=layout))(params, x, ctx)
    return np.asarray(eps)

ref_eps = _one_forward()
unet_mod._apply_attention = attn_fused_qkv
fused_eps = _one_forward()
err = float(np.abs(ref_eps.astype(np.float32) - fused_eps.astype(np.float32)).max())
print(f"qkv-fused parity max|Δeps| = {err:.3e}", flush=True)
if cfg is TINY:
    # On CPU the fused projection is the same dots split after — today this
    # measures exactly 0.0, and the tolerance exists only so an XLA upgrade
    # that re-tiles the wider contraction can't fail the smoke lane
    # spuriously; 1e-6 is still ~100× below any real fusion bug. (On TPU
    # the wider contraction may tile differently, so the smoke lane is
    # where near-exactness is enforced; the chip run still prints its err.)
    assert err <= 1e-6, f"qkv-fused projection diverged: max|Δeps|={err}"
time_scan(4, "qkv-fused projections")
unet_mod._apply_attention = orig_attn

if "--all" not in sys.argv:
    sys.exit(0)

# --- round-2 record: small-site attention lowerings (rejected; PERF.md) ---

# 6. dot_product_attention for ALL untouched sites
def fused_dpa(q, k, v, scale, mask=None):
    if mask is None:
        out = jax.nn.dot_product_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), scale=scale)
        return out.transpose(0, 2, 1, 3)
    return orig_fused(q, k, v, scale, mask)
nn_mod.fused_attention = fused_dpa
unet_mod.nn.fused_attention = fused_dpa
time_scan(4, "dot_product_attention all")

# 7. flash kernel down to S>=1024 (32² sites), dpa below
from jax.experimental.pallas.ops.tpu import flash_attention as _fa
def fused_flash1024(q, k, v, scale, mask=None):
    s_q, s_k = q.shape[-2], k.shape[-2]
    if mask is None and s_q == s_k and s_q >= 1024:
        blk = next((b for b in (1024, 512, 256) if s_q % b == 0), 0)
        if blk:
            sizes = _fa.BlockSizes(block_q=blk, block_k_major=blk, block_k=blk,
                block_b=1, block_q_major_dkv=blk, block_k_major_dkv=blk,
                block_q_dkv=blk, block_k_dkv=blk)
            return _fa.flash_attention(q, k, v, causal=False, sm_scale=scale,
                                       block_sizes=sizes)
    return fused_dpa(q, k, v, scale, mask)
nn_mod.fused_attention = fused_flash1024
unet_mod.nn.fused_attention = fused_flash1024
time_scan(4, "flash>=1024 + dpa")
nn_mod.fused_attention = orig_fused
unet_mod.nn.fused_attention = orig_fused
