#!/bin/bash
# Priority-ordered chip-window runner: the axon relay's healthy windows are
# scarce (two multi-hour outages in two days), so when it recovers, run the
# highest-value jobs first, each with its own leash. The trace capture is
# deliberately NOT here — stopping a trace can wedge the lease; run
# prof_trace.py manually, last, when nothing else is pending.
#
# Every gap-filling step gates on the committed archive actually missing
# its artifact, so the script stays correct across days: once the A/Bs and
# the two narrowed secondaries have landed, a future window goes straight
# to the full headline bench.
#
#   tools/profiling/chip_window.sh [logdir]      # run now
#
set -u
cd "$(dirname "$0")/../.."
L="${1:-/tmp/chipwindow}"
mkdir -p "$L"

run() { # name timeout cmd...
  local name="$1" leash="$2"; shift 2
  echo "=== $name (leash ${leash}s) $(date -u +%H:%M:%S)" | tee -a "$L/runner.log"
  timeout "$leash" "$@" > "$L/$name.log" 2>&1
  local rc=$?
  echo "=== $name rc=$rc $(date -u +%H:%M:%S)" | tee -a "$L/runner.log"
}

# True iff any committed on-chip artifact already carries the metric key.
have_metric() {
  python - "$1" <<'PY'
import glob, json, sys
for p in glob.glob("bench_runs/*_onchip.json"):
    try:
        if sys.argv[1] in json.load(open(p)):
            sys.exit(0)
    except Exception:
        pass
sys.exit(1)
PY
}

# 1. A/B experiments (upsample, head-dim pad64/pad128, qkv-fuse, batch
#    scaling, VAE dtype) — once per repo state; the log is preserved as a
#    committed artifact, which is also the re-run gate.
if ! ls bench_runs/*_experiments.log >/dev/null 2>&1; then
  run experiments 1500 python tools/profiling/prof_experiments.py
  grep -q "ms/step" "$L/experiments.log" && \
    cp "$L/experiments.log" "bench_runs/$(date -u +%F)_experiments.log"
fi
# 2+3. Narrowed runs for any secondary the archive has never measured, one
#    invocation each so each gets the full child budget even cold-cache
#    (nullinv's two programs are the most expensive compile in the bench).
#    Narrowed runs skip the headline (value-0 line + "narrowed" marker);
#    the same-day merge absorbs the new keys into a full artifact.
have_metric nullinv_s_per_image || \
  run bench_nullinv 1800 env P2P_BENCH_SECONDARIES=nullinv python bench.py
have_metric ldm256_8prompt_imgs_per_s || \
  run bench_ldm256 1800 env P2P_BENCH_SECONDARIES=ldm256 python bench.py
# 4. Full driver-metric refresh (also re-primes every program's cache for
#    the driver's round-end run). -u: an operator-exported narrowing from a
#    manual recovery run must not silently narrow the refresh.
run bench 1800 env -u P2P_BENCH_SECONDARIES python bench.py
# 5. Scan unroll probe.
run unroll 1200 python tools/profiling/prof_unroll.py
echo "window done; logs in $L" | tee -a "$L/runner.log"
