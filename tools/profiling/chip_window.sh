#!/bin/bash
# Priority-ordered chip-window runner: the axon relay's healthy windows are
# scarce (two multi-hour outages in two days), so when it recovers, run the
# highest-value jobs first, each with its own leash. The trace capture is
# deliberately NOT here — stopping a trace can wedge the lease; run
# prof_trace.py manually, last, when nothing else is pending.
#
# Every gap-filling step gates on the committed archive actually missing
# its artifact, so the script stays correct across days: once the A/Bs and
# the narrowed secondaries have landed, a future window goes straight to
# the full headline bench.
#
#   tools/profiling/chip_window.sh [logdir]      # run now
#
set -u
cd "$(dirname "$0")/../.."
L="${1:-/tmp/chipwindow}"
mkdir -p "$L"

run() { # name timeout cmd...
  local name="$1" leash="$2"; shift 2
  echo "=== $name (leash ${leash}s) $(date -u +%H:%M:%S)" | tee -a "$L/runner.log"
  timeout "$leash" "$@" > "$L/$name.log" 2>&1
  local rc=$?
  echo "=== $name rc=$rc $(date -u +%H:%M:%S)" | tee -a "$L/runner.log"
}

# Direct-jax profiling tools refuse a CPU-demoted backend
# (_bench_common.require_accelerator) rather than print garbage; when a
# step dies that way it usually means we launched inside the ~4.5-min
# lease-release hole (measured 2026-08-01), so retry after the hole has
# passed. The tool itself is the probe — a separate probe client's exit
# would just re-open the hole it was checking for. bench.py steps don't
# need this: their parent probe rides the hole out internally.
#
# Retry loop (ADVICE r5): up to 3 attempts total, and the sleep is keyed
# off the REFUSAL timestamp (the log's mtime — when the refused tool
# exited), not off "now": a fixed 300s from an arbitrary later point can
# land the retry inside a fresh hole that the previous attempt's own exit
# just re-opened. We wait until ~330s after the refusal, which clears the
# measured ~4.5-min hole with margin however long the bookkeeping between
# attempts took.
run_tool() { # name leash cmd...
  local name="$1" attempt ref_ts now wait
  run "$@"
  for attempt in 2 3; do
    grep -q "profiling refused" "$L/$name.log" || return 0
    ref_ts=$(stat -c %Y "$L/$name.log" 2>/dev/null || date +%s)
    now=$(date +%s)
    wait=$(( ref_ts + 330 - now ))
    [ "$wait" -lt 10 ] && wait=10
    echo "=== $name hit the lease hole; attempt $attempt/3 in ${wait}s" \
      | tee -a "$L/runner.log"
    sleep "$wait"
    run "$@"
  done
  if grep -q "profiling refused" "$L/$name.log"; then
    echo "=== $name still refused after 3 attempts; moving on" \
      | tee -a "$L/runner.log"
  fi
}

# The experiments artifact the step-1/1b/5 gates key off (newest if several).
exp_log() { ls -t bench_runs/*_experiments.log 2>/dev/null | head -1; }

# True iff any committed on-chip artifact already carries the metric key.
have_metric() {
  python - "$1" <<'PY'
import glob, json, sys
for p in glob.glob("bench_runs/*_onchip.json"):
    try:
        if sys.argv[1] in json.load(open(p)):
            sys.exit(0)
    except Exception:
        pass
sys.exit(1)
PY
}

# 1. A/B experiments (upsample, head-dim pad64/pad128, qkv-fuse, batch
#    scaling, VAE dtype) — once per repo state; the log is preserved as a
#    committed artifact, which is also the re-run gate. Gate on full-suite
#    content (the pad probe only the full run prints), not file existence:
#    steps 1b/5 may have fallback-created a qkv-/unroll-only log when this
#    step lost its window, and that must not suppress the suite forever.
#    pad128 is the last full-suite-only experiment (5c qkv has step 1b),
#    so its presence is what "suite complete" actually means — a run that
#    crashed mid-suite re-runs.
if ! grep -q "flash head_dim pad128" bench_runs/*_experiments.log 2>/dev/null; then
  run_tool experiments 1500 python tools/profiling/prof_experiments.py
  if grep -q "ms/step" "$L/experiments.log"; then
    t="bench_runs/$(date -u +%F)_experiments.log"
    if [ -f "$t" ]; then
      # Same-day fallback-created log (qkv/unroll sections): append, don't
      # clobber someone else's scarce measurements.
      { echo; echo "--- full A/B suite, $(date -u +%F) ---";
        cat "$L/experiments.log"; } >> "$t"
    else
      cp "$L/experiments.log" "$t"
    fi
  fi
fi
# 1b. The qkv-fused A/B crashed out of the 2026-08-01 experiments run
# (harness dtype bug, since fixed + smoke-laned); an archived log may gate
# step 1 while still lacking the qkv *timing* (the crash traceback quotes
# the label, so match the timing line, not the label) — capture it
# separately and append to the committed artifact.
if ! grep -q "qkv-fused projections.*ms/step" bench_runs/*_experiments.log 2>/dev/null; then
  run_tool qkv 1200 python tools/profiling/prof_experiments.py --qkv
  if grep -q "qkv-fused projections.*ms/step" "$L/qkv.log"; then
    target="$(exp_log)"
    [ -z "$target" ] && target="bench_runs/$(date -u +%F)_experiments.log"
    { echo; echo "--- qkv A/B re-run (fixed harness), $(date -u +%F) ---";
      grep -a "ms/step\|parity" "$L/qkv.log"; } >> "$target"
  fi
fi
# 2+3. Narrowed runs for any secondary the archive has never measured, one
#    invocation each so each gets the full child budget even cold-cache
#    (nullinv's two programs are the most expensive compile in the bench).
#    Narrowed runs skip the headline (value-0 line + "narrowed" marker);
#    the same-day merge absorbs the new keys into a full artifact.
have_metric nullinv_s_per_image || \
  run bench_nullinv 1800 env P2P_BENCH_SECONDARIES=nullinv python bench.py
have_metric ldm256_8prompt_imgs_per_s || \
  run bench_ldm256 1800 env P2P_BENCH_SECONDARIES=ldm256 python bench.py
# 4. Full driver-metric refresh (also re-primes every program's cache for
#    the driver's round-end run). -u: an operator-exported narrowing from a
#    manual recovery run must not silently narrow the refresh.
run bench 1800 env -u P2P_BENCH_SECONDARIES python bench.py
# 5. Scan unroll probe — same once-per-repo-state artifact gating as the
#    A/Bs (measured 2026-08-01: unroll=1 wins; appended to the archive).
if ! grep -q "unroll=" bench_runs/*_experiments.log 2>/dev/null; then
  run_tool unroll 1200 python tools/profiling/prof_unroll.py
  if grep -q "unroll=.*ms/step" "$L/unroll.log"; then
    target="$(exp_log)"
    [ -z "$target" ] && target="bench_runs/$(date -u +%F)_experiments.log"
    { echo; echo "--- scan unroll probe, $(date -u +%F) ---";
      grep -a "unroll=" "$L/unroll.log"; } >> "$target"
  fi
fi
echo "window done; logs in $L" | tee -a "$L/runner.log"
