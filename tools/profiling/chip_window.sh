#!/bin/bash
# Priority-ordered chip-window runner: the axon relay's healthy windows are
# scarce (two multi-hour outages in two days), so when it recovers, run the
# highest-value jobs first, each with its own leash. The trace capture is
# deliberately NOT here — stopping a trace can wedge the lease; run
# prof_trace.py manually, last, when nothing else is pending.
#
#   tools/profiling/chip_window.sh [logdir]      # run now
#
set -u
cd "$(dirname "$0")/../.."
L="${1:-/tmp/chipwindow}"
mkdir -p "$L"

run() { # name timeout cmd...
  local name="$1" leash="$2"; shift 2
  echo "=== $name (leash ${leash}s) $(date -u +%H:%M:%S)" | tee -a "$L/runner.log"
  timeout "$leash" "$@" > "$L/$name.log" 2>&1
  local rc=$?
  echo "=== $name rc=$rc $(date -u +%H:%M:%S)" | tee -a "$L/runner.log"
}

# 1. The driver metric + cache priming for every program bench now times
#    (incl. the dpm-batched and null-inversion secondaries).
run bench 1800 python bench.py
# 2. A/B experiments: upsample, head-dim pad, batch scaling, VAE dtype.
run experiments 1500 python tools/profiling/prof_experiments.py
# 3. Scan unroll probe.
run unroll 1200 python tools/profiling/prof_unroll.py
echo "window done; logs in $L" | tee -a "$L/runner.log"
