"""Measure: bf16-arithmetic GroupNorm effect + flash block-size sweep."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
import jax, jax.numpy as jnp, numpy as np
from p2p_tpu.models import SD14, init_unet, unet_layout
from p2p_tpu.models import nn as nn_mod
from p2p_tpu.models.unet import apply_unet
import p2p_tpu.models.unet as unet_mod
from jax.experimental.pallas.ops.tpu import flash_attention as _fa

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_common import require_accelerator

require_accelerator()

cfg = SD14
layout = unet_layout(cfg.unet)
params = init_unet(jax.random.PRNGKey(0), cfg.unet)
s = cfg.latent_size
B = 4
x = jnp.ones((B, s, s, cfg.unet.in_channels), jnp.bfloat16)
ctx = jnp.ones((B, cfg.unet.context_len, cfg.unet.context_dim), jnp.bfloat16)

def bench(label):
    @jax.jit
    def scan(params, x, ctx):
        def body(h, t):
            eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
            return eps, None
        out, _ = jax.lax.scan(body, x, jnp.arange(50, dtype=jnp.int32))
        return out
    t0 = time.perf_counter(); np.asarray(scan(params, x, ctx)); c = time.perf_counter()-t0
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter(); np.asarray(scan(params, x, ctx))
        best = min(best, time.perf_counter()-t0)
    print(f"{label:40s}: {best/50*1000:6.2f} ms/step (compile {c:.0f}s)", flush=True)

bench("new GN, flash blk1024 (>=2048)")

orig = nn_mod.fused_attention
def make_fused(minseq, bq, bk):
    def fused(q, k, v, scale, mask=None):
        s_q, s_k = q.shape[-2], k.shape[-2]
        if mask is None and s_q == s_k and s_q >= minseq and s_q % bq == 0 and s_q % bk == 0:
            sizes = _fa.BlockSizes(block_q=bq, block_k_major=bk, block_k=bk,
                block_b=1, block_q_major_dkv=bq, block_k_major_dkv=bk,
                block_q_dkv=bq, block_k_dkv=bk)
            return _fa.flash_attention(q, k, v, causal=False, sm_scale=scale,
                                       block_sizes=sizes)
        return orig(q, k, v, scale, mask)
    return fused

for (minseq, bq, bk) in [(2048, 2048, 1024), (2048, 512, 1024), (2048, 1024, 512),
                         (2048, 512, 512), (1024, 1024, 1024), (1024, 512, 512)]:
    f = make_fused(minseq, bq, bk)
    nn_mod.fused_attention = f
    unet_mod.nn.fused_attention = f
    bench(f"flash minseq={minseq} bq={bq} bk={bk}")
nn_mod.fused_attention = orig
unet_mod.nn.fused_attention = orig
