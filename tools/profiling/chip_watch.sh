#!/bin/bash
# Relay-recovery watcher: probe the axon relay every ~3 minutes; the moment a
# tiny jax program answers, run the priority chip jobs (chip_window.sh) and
# exit. Designed to run in the background all session so a scarce healthy
# window is never missed (see ROUND3.md: two multi-hour outages in two days).
#
#   tools/profiling/chip_watch.sh [logdir]
set -u
cd "$(dirname "$0")/../.."
L="${1:-/tmp/chipwindow}"
mkdir -p "$L"
echo "watcher start $(date -u +%H:%M:%S)" >> "$L/watch.log"
while true; do
  # Stage 1 (cheap): the relay's remote-compile port. rc=7 → relay dead
  # (SKILL.md failure modes); only an accepting port warrants the python
  # probe, which can itself hang minutes on a wedged lease.
  # Connect-level predicate (same as bench.py's _relay_port_accepts): only
  # rc 7 (refused) / 28 (timeout) mean the port is dead; any post-connect
  # outcome (incl. resets) is worth the real python probe.
  curl -s -o /dev/null --max-time 5 http://127.0.0.1:8083/
  rc=$?
  if [ "$rc" -ne 7 ] && [ "$rc" -ne 28 ]; then
    timeout 90 python - <<'EOF' > /dev/null 2>&1
import jax
assert jax.devices()[0].platform != "cpu"
EOF
    rc=$?
  else
    rc=100  # relay port not accepting
  fi
  echo "probe rc=$rc $(date -u +%H:%M:%S)" >> "$L/watch.log"
  if [ "$rc" -eq 0 ]; then
    echo "RELAY UP $(date -u +%H:%M:%S) - running chip_window.sh" >> "$L/watch.log"
    bash tools/profiling/chip_window.sh "$L"
    echo "chip_window done rc=$? $(date -u +%H:%M:%S)" >> "$L/watch.log"
    exit 0
  fi
  sleep 170
done
