"""Time SD14 50-step sampling variants on the real TPU chip.

Variants isolate the cost components:
  identity     — no controller: all sites fused (model ceiling)
  edit_store   — AttentionReplace, store=True (current bench default)
  edit_nostore — AttentionReplace, store=False
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

from p2p_tpu.controllers import factory
from p2p_tpu.engine.sampler import Pipeline, text2image
from p2p_tpu.models import SD14, init_text_encoder, init_unet
from p2p_tpu.models import vae as vae_mod
from p2p_tpu.utils.tokenizer import HashWordTokenizer

# Siblings insert the script dir explicitly: when a launcher runs this file
# by absolute path from another cwd with an inherited sys.path[0], the
# implicit script-dir entry is not guaranteed — the _bench_common import
# must not depend on it (ADVICE round-5 finding).
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_common import require_accelerator

require_accelerator()

NUM_STEPS = 50
cfg = SD14
tok = HashWordTokenizer(model_max_length=cfg.text.max_length)
pipe = Pipeline(
    config=cfg,
    unet_params=init_unet(jax.random.PRNGKey(0), cfg.unet),
    text_params=init_text_encoder(jax.random.PRNGKey(1), cfg.text),
    vae_params=vae_mod.init_vae(jax.random.PRNGKey(2), cfg.vae),
    tokenizer=tok,
)
prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]

def ctrl(store):
    return factory.attention_replace(
        prompts, NUM_STEPS, cross_replace_steps=0.8, self_replace_steps=0.4,
        tokenizer=tok, self_max_pixels=16 * 16, max_len=cfg.text.max_length,
        store=store)

variants = {
    "identity": None,
    "edit_store": ctrl(True),
    "edit_nostore": ctrl(False),
}

for name, controller in variants.items():
    def run(seed):
        img, _, _ = text2image(pipe, prompts, controller, num_steps=NUM_STEPS,
                               rng=jax.random.PRNGKey(seed), dtype=jnp.bfloat16)
        return np.asarray(img)
    t0 = time.perf_counter()
    run(0)
    compile_s = time.perf_counter() - t0
    times = []
    for i in range(3):
        t0 = time.perf_counter()
        run(i + 1)
        times.append(time.perf_counter() - t0)
    best = min(times)
    print(f"{name:13s} compile {compile_s:6.1f}s  best {best*1000:8.1f} ms "
          f"-> {2/best:6.3f} img/s  ({best/NUM_STEPS*1000:6.2f} ms/step incl VAE)",
          flush=True)
