"""Shared SD14 50-step scan benchmark (currently used by prof_flags.py; the
other prof_* scripts are frozen records of specific round-2 experiments —
their inline copies document exactly what was measured then)."""
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def sd14_scan_ms_per_step(batch: int = 4, steps: int = 50, repeats: int = 2) -> float:
    """Best-of-N ms/step for the jitted SD14 U-Net scan (identity controller)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.models import SD14, init_unet, unet_layout
    from p2p_tpu.models.unet import apply_unet
    from p2p_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()

    cfg = SD14
    layout = unet_layout(cfg.unet)
    params = init_unet(jax.random.PRNGKey(0), cfg.unet)
    s = cfg.latent_size
    x = jnp.ones((batch, s, s, cfg.unet.in_channels), jnp.bfloat16)
    ctx = jnp.ones((batch, cfg.unet.context_len, cfg.unet.context_dim),
                   jnp.bfloat16)

    @jax.jit
    def scan(params, x, ctx):
        def body(h, t):
            eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
            return eps, None
        out, _ = jax.lax.scan(body, x, jnp.arange(steps, dtype=jnp.int32))
        return out

    np.asarray(scan(params, x, ctx))  # compile
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(scan(params, x, ctx))
        best = min(best, time.perf_counter() - t0)
    return best / steps * 1000.0
