"""Shared SD14 50-step scan benchmark, used by prof_flags.py and
prof_unroll.py. prof_experiments.py keeps its own inline copy because it
monkeypatches model internals between timings; prof_variants/prof_breakdown/
prof_gn_flash are frozen records of specific round-2 experiments."""
import os
import sys
import time
from functools import partial

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def require_accelerator():
    """Exit rather than time SD14 programs on a silently-demoted CPU backend.

    When the axon plugin fails init (relay death, or the ~4.5-min lease
    -release hole after another chip client exits — measured 2026-08-01),
    jax falls back to CPU with only a warning, and a profiling tool would
    print plausible-looking but meaningless numbers into a log that
    chip_window.sh may archive. P2P_PROF_ALLOW_CPU=1 overrides for anyone
    who really wants host timings."""
    import jax

    if (jax.devices()[0].platform == "cpu"
            and os.environ.get("P2P_PROF_ALLOW_CPU") != "1"):
        sys.exit("profiling refused: jax backend is cpu (accelerator plugin "
                 "failed init or none configured); set P2P_PROF_ALLOW_CPU=1 "
                 "to time the host")


def sd14_scan_ms_per_step(batch: int = 4, steps: int = 50, repeats: int = 2,
                          compiler_options=None, unroll: int = 1) -> float:
    """Best-of-N ms/step for the jitted SD14 U-Net scan (identity controller).

    ``compiler_options`` are forwarded to ``jax.jit`` (PJRT passes them to the
    server-side TPU compiler — the working route for ``xla_tpu_*`` options on
    the axon platform, where XLA_FLAGS is parsed by a client that doesn't
    know them). ``unroll`` is forwarded to ``lax.scan``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.models import SD14, init_unet, unet_layout
    from p2p_tpu.models.unet import apply_unet
    from p2p_tpu.utils.cache import enable_persistent_cache

    require_accelerator()
    enable_persistent_cache()

    cfg = SD14
    layout = unet_layout(cfg.unet)
    params = init_unet(jax.random.PRNGKey(0), cfg.unet)
    s = cfg.latent_size
    x = jnp.ones((batch, s, s, cfg.unet.in_channels), jnp.bfloat16)
    ctx = jnp.ones((batch, cfg.unet.context_len, cfg.unet.context_dim),
                   jnp.bfloat16)

    @partial(jax.jit, compiler_options=compiler_options)
    def scan(params, x, ctx):
        def body(h, t):
            eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
            return eps, None
        out, _ = jax.lax.scan(body, x, jnp.arange(steps, dtype=jnp.int32),
                              unroll=unroll)
        return out

    np.asarray(scan(params, x, ctx))  # compile
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(scan(params, x, ctx))
        best = min(best, time.perf_counter() - t0)
    return best / steps * 1000.0
