"""Capture a jax.profiler trace of the UNet scan and dump HLO op stats."""
import os, sys, time, glob, os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
import jax, jax.numpy as jnp, numpy as np
from p2p_tpu.models import SD14, init_unet, unet_layout
from p2p_tpu.models.unet import apply_unet

cfg = SD14
layout = unet_layout(cfg.unet)
params = init_unet(jax.random.PRNGKey(0), cfg.unet)
s = cfg.latent_size
B = 4
x = jnp.ones((B, s, s, cfg.unet.in_channels), jnp.bfloat16)
ctx = jnp.ones((B, cfg.unet.context_len, cfg.unet.context_dim), jnp.bfloat16)

@jax.jit
def scan(params, x, ctx):
    def body(h, t):
        eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
        return eps, None
    out, _ = jax.lax.scan(body, x, jnp.arange(50, dtype=jnp.int32))
    return out

np.asarray(scan(params, x, ctx))  # compile
logdir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "trace_out")
os.system(f"rm -rf {logdir}")
jax.profiler.start_trace(logdir)
np.asarray(scan(params, x, ctx))
jax.profiler.stop_trace()

xplanes = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
print("xplane:", xplanes, flush=True)
from tensorboard_plugin_profile.convert import raw_to_tool_data
data, _ = raw_to_tool_data.xspace_to_tool_data(xplanes, "framework_op_stats", {})
open(os.path.join(os.path.dirname(os.path.abspath(__file__)), "op_stats.out"), "wb").write(
    data if isinstance(data, bytes) else data.encode())
print("wrote op_stats.out", flush=True)
