"""Capture a jax.profiler device trace of the U-Net scan and aggregate the
per-op time by category, parsing the chrome-format trace directly (the
tensorboard_plugin_profile converter is broken against the installed TF —
see .claude/skills/verify/SKILL.md).

    python tools/profiling/prof_trace.py            # capture + parse
    python tools/profiling/prof_trace.py --parse D  # re-parse existing dir

NOTE: stopping a trace through the axon tunnel can wedge the TPU lease
(>30 min observed) — run this LAST in a chip window.
"""
import glob
import gzip
import json
import os
import re
import sys
from collections import defaultdict

HERE = os.path.dirname(os.path.abspath(__file__))

# Coarse hlo-category buckets, matched against event names when the trace
# has no explicit category args (order matters — first match wins).
_BUCKETS = (
    ("flash-attention", re.compile(r"flash|custom-call", re.I)),
    ("convolution", re.compile(r"conv", re.I)),
    ("data formatting", re.compile(r"copy|transpose|reshape|bitcast|slice|"
                                   r"concatenate|pad|gather|scatter|"
                                   r"dynamic-update", re.I)),
    ("matmul", re.compile(r"dot|einsum", re.I)),
    ("loop fusion", re.compile(r"fusion|loop", re.I)),
    ("reduce/norm", re.compile(r"reduce|norm|softmax", re.I)),
    ("infeed/outfeed", re.compile(r"infeed|outfeed|transfer", re.I)),
)


def parse_trace_dir(logdir: str):
    """Aggregate complete ('X') events from every *.trace.json.gz under
    ``logdir`` by device lane and category bucket; print a share table."""
    paths = sorted(glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True))
    if not paths:
        print(f"no *.trace.json.gz under {logdir}", file=sys.stderr)
        return 1
    by_cat = defaultdict(float)
    lanes = defaultdict(float)
    total = 0.0
    for path in paths:
        with gzip.open(path, "rt") as f:
            doc = json.load(f)
        pid_names = {}
        tid_names = {}
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "M":
                continue
            if ev.get("name") == "process_name":
                pid_names[ev.get("pid")] = ev.get("args", {}).get("name", "")
            elif ev.get("name") == "thread_name":
                tid_names[(ev.get("pid"), ev.get("tid"))] = (
                    ev.get("args", {}).get("name", ""))
        # Device pids carry several lanes (XLA Ops, XLA Modules, Steps…);
        # the Modules/Steps rows are ENVELOPES around the same ops — summing
        # every lane double-counts 2-3×. Keep only the per-op lane when one
        # is named; fall back to all lanes for traces without thread names.
        op_tids = {pt for pt, n in tid_names.items()
                   if re.search(r"xla ops", n, re.I)}
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            pid, tid = ev.get("pid"), ev.get("tid")
            lane = pid_names.get(pid, "")
            # Device processes only — host-side python/runtime rows would
            # count dispatch time as device time.
            if lane and not re.search(r"tpu|device|/device|xla", lane, re.I):
                continue
            if op_tids and (pid, tid) not in op_tids:
                continue
            dur = float(ev.get("dur", 0.0))  # microseconds
            name = ev.get("name", "")
            args = ev.get("args", {}) or {}
            cat = args.get("hlo_category") or next(
                (b for b, rx in _BUCKETS if rx.search(name)), "other")
            by_cat[cat] += dur
            lanes[f"{lane or '?'}/{tid_names.get((pid, tid), tid)}"] += dur
            total += dur
    if not total:
        print("no device events parsed", file=sys.stderr)
        return 1
    print(f"lanes: {dict(lanes)}")
    print(f"{'category':24s} {'ms':>10s} {'share':>7s}")
    for cat, us in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        print(f"{cat:24s} {us / 1e3:10.1f} {us / total:7.1%}")
    print(f"{'TOTAL':24s} {total / 1e3:10.1f}")
    return 0


def capture(logdir: str):
    sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2p_tpu.models import SD14, init_unet, unet_layout
    from p2p_tpu.models.unet import apply_unet
    from p2p_tpu.utils.cache import enable_persistent_cache

    from _bench_common import require_accelerator

    require_accelerator()
    enable_persistent_cache()
    cfg = SD14
    layout = unet_layout(cfg.unet)
    params = init_unet(jax.random.PRNGKey(0), cfg.unet)
    s = cfg.latent_size
    B = 4
    x = jnp.ones((B, s, s, cfg.unet.in_channels), jnp.bfloat16)
    ctx = jnp.ones((B, cfg.unet.context_len, cfg.unet.context_dim),
                   jnp.bfloat16)

    @jax.jit
    def scan(params, x, ctx):
        def body(h, t):
            eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
            return eps, None
        out, _ = jax.lax.scan(body, x, jnp.arange(50, dtype=jnp.int32))
        return out

    np.asarray(scan(params, x, ctx))  # compile
    import shutil
    shutil.rmtree(logdir, ignore_errors=True)
    jax.profiler.start_trace(logdir)
    np.asarray(scan(params, x, ctx))
    jax.profiler.stop_trace()
    print(f"trace captured under {logdir}", flush=True)


def main():
    if "--parse" in sys.argv:
        return parse_trace_dir(sys.argv[sys.argv.index("--parse") + 1])
    logdir = os.path.join(HERE, "trace_out")
    capture(logdir)
    return parse_trace_dir(logdir)


if __name__ == "__main__":
    sys.exit(main())
