"""Break down where the 48ms/step goes: UNet vs VAE vs text-encode; FLOPs."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_common import require_accelerator

require_accelerator()
d = jax.devices()[0]
print(f"device: {d.device_kind} platform={d.platform}", flush=True)

from p2p_tpu.models import SD14, init_unet, unet_layout
from p2p_tpu.models import vae as vae_mod
from p2p_tpu.models.unet import apply_unet

cfg = SD14
layout = unet_layout(cfg.unet)
params = init_unet(jax.random.PRNGKey(0), cfg.unet)
B = 4  # CFG-doubled 2-prompt batch
s = cfg.latent_size
dtype = jnp.bfloat16

x = jnp.ones((B, s, s, cfg.unet.in_channels), dtype)
ctx = jnp.ones((B, cfg.unet.context_len, cfg.unet.context_dim), dtype)

@jax.jit
def unet_scan(params, x, ctx):
    def body(h, t):
        eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
        return eps, None
    out, _ = jax.lax.scan(body, x, jnp.arange(50, dtype=jnp.int32))
    return out

# FLOPs of a single forward — via the shared cost-observatory helper
# (obs/costmodel.py), which owns the dict-vs-list cost_analysis() API-drift
# guard and the memory_analysis() byte budget.
from p2p_tpu.obs import costmodel

single = jax.jit(lambda p, x, c: apply_unet(p, cfg.unet, x, jnp.int32(1), c, layout=layout)[0])
card = costmodel.card_from_compiled(single.lower(params, x, ctx).compile(),
                                    program=f"unet_step_b{B}")
flops = card.flops
peaks = costmodel.detect_peaks()
roof = costmodel.roofline(card.flops, card.bytes_accessed, peaks)
print(f"single fwd flops (batch {B}): {flops/1e12:.3f} TF; "
      f"{card.bytes_accessed/1e9:.2f} GB accessed; {roof['bound']}-bound, "
      f"predicted {roof['predicted_ms']:.1f} ms/step at "
      f"{peaks.platform} peaks ({peaks.source})", flush=True)

t0 = time.perf_counter(); r = np.asarray(unet_scan(params, x, ctx)); print(f"unet_scan compile {time.perf_counter()-t0:.1f}s", flush=True)
for _ in range(2):
    t0 = time.perf_counter(); r = np.asarray(unet_scan(params, x, ctx)); dt = time.perf_counter()-t0
    mfu = costmodel.mfu_pct(flops, dt / 50 * 1000.0, peaks)
    print(f"unet 50-step scan: {dt*1000:.0f} ms -> {dt/50*1000:.2f} ms/step, "
          f"{flops*50/dt/1e12:.1f} TF/s"
          + (f" = {mfu:.1f}% MFU" if mfu is not None else ""), flush=True)

# VAE decode timing (f32, as the pipeline runs it)
vparams = vae_mod.init_vae(jax.random.PRNGKey(2), cfg.vae)
lat = jnp.ones((2, s, s, cfg.unet.in_channels), jnp.float32)
vdec = jax.jit(lambda p, l: vae_mod.to_uint8(vae_mod.decode(p, cfg.vae, l)))
t0 = time.perf_counter(); np.asarray(vdec(vparams, lat)); print(f"vae compile {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter(); np.asarray(vdec(vparams, lat)); print(f"vae decode: {(time.perf_counter()-t0)*1000:.0f} ms", flush=True)
