"""Break down where the 48ms/step goes: UNet vs VAE vs text-encode; FLOPs."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_common import require_accelerator

require_accelerator()
d = jax.devices()[0]
print(f"device: {d.device_kind} platform={d.platform}", flush=True)

from p2p_tpu.models import SD14, init_unet, unet_layout
from p2p_tpu.models import vae as vae_mod
from p2p_tpu.models.unet import apply_unet

cfg = SD14
layout = unet_layout(cfg.unet)
params = init_unet(jax.random.PRNGKey(0), cfg.unet)
B = 4  # CFG-doubled 2-prompt batch
s = cfg.latent_size
dtype = jnp.bfloat16

x = jnp.ones((B, s, s, cfg.unet.in_channels), dtype)
ctx = jnp.ones((B, cfg.unet.context_len, cfg.unet.context_dim), dtype)

@jax.jit
def unet_scan(params, x, ctx):
    def body(h, t):
        eps, _ = apply_unet(params, cfg.unet, h, t, ctx, layout=layout)
        return eps, None
    out, _ = jax.lax.scan(body, x, jnp.arange(50, dtype=jnp.int32))
    return out

# FLOPs of a single forward
single = jax.jit(lambda p, x, c: apply_unet(p, cfg.unet, x, jnp.int32(1), c, layout=layout)[0])
lowered = single.lower(params, x, ctx)
compiled = lowered.compile()
ca = compiled.cost_analysis()
flops = ca.get("flops", 0.0) if isinstance(ca, dict) else ca[0]["flops"]
print(f"single fwd flops (batch {B}): {flops/1e12:.3f} TF", flush=True)

t0 = time.perf_counter(); r = np.asarray(unet_scan(params, x, ctx)); print(f"unet_scan compile {time.perf_counter()-t0:.1f}s", flush=True)
for _ in range(2):
    t0 = time.perf_counter(); r = np.asarray(unet_scan(params, x, ctx)); dt = time.perf_counter()-t0
    print(f"unet 50-step scan: {dt*1000:.0f} ms -> {dt/50*1000:.2f} ms/step, "
          f"{flops*50/dt/1e12:.1f} TF/s", flush=True)

# VAE decode timing (f32, as the pipeline runs it)
vparams = vae_mod.init_vae(jax.random.PRNGKey(2), cfg.vae)
lat = jnp.ones((2, s, s, cfg.unet.in_channels), jnp.float32)
vdec = jax.jit(lambda p, l: vae_mod.to_uint8(vae_mod.decode(p, cfg.vae, l)))
t0 = time.perf_counter(); np.asarray(vdec(vparams, lat)); print(f"vae compile {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter(); np.asarray(vdec(vparams, lat)); print(f"vae decode: {(time.perf_counter()-t0)*1000:.0f} ms", flush=True)
