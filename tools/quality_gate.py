"""Golden quality gate: re-run the pinned tiny configs and diff against
``tests/golden/*.npz``, exiting nonzero on drift.

The golden pytest (tests/test_golden.py) answers "did THIS commit change
numerics"; this tool is the standalone CI/tooling form of the same contract —
runnable outside pytest (e.g. as a pre-merge gate or from a perf-tuning
loop), reporting MSE and max-abs per config, with thresholds on the command
line. It reuses test_golden's case builders so the two can never drift apart,
and adds the phase-gate drift check (gated latents vs
``tests/golden/phase_gate.npz``) so an attention-cache regression fails the
gate even when ungated sampling is untouched.

    python tools/quality_gate.py                 # all configs, default bounds
    python tools/quality_gate.py --only replace,dpm --max-abs 3 --mse 0.25

Wired into the suite as a ``slow``-marked pytest
(tests/test_quality_gate.py) so tier-1 (-m 'not slow') stays fast.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import warnings

# Force the deterministic CPU backend before any jax import: quality is
# platform-independent, and the goldens are pinned on CPU (same shared
# helper as the analyzer drivers). The virtual 8-device platform gives
# the mesh_parity and shardcheck checks a real mesh to span; it changes
# nothing for the single-device checks (device 0 numerics are identical).
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from p2p_tpu.utils.platform import force_cpu_platform  # noqa: E402

force_cpu_platform()

from p2p_tpu.utils.cache import default_cache_dir  # noqa: E402

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      default_cache_dir(hash_xla_flags=False))

import numpy as np  # noqa: E402


def _cases():
    """test_golden's case registry + the tiny pipeline it runs against."""
    from tests.test_golden import CASES, GOLDEN_DIR, _pipe
    from p2p_tpu.models import TINY

    return CASES, GOLDEN_DIR, _pipe(TINY)


def _phase_gate_drift():
    """(mse, max_abs) of gate=0.5T latents vs the ungated latents — the
    ISSUE 1 drift contract (threshold 1e-2), checked end to end. Mirrors
    test_phase_cache's foreign-platform fallback: when the in-session
    ungated run itself disagrees with the pinned npz (different BLAS/ISA
    than the pinning host), drift is measured against the in-session
    baseline — the property gated here is what the *gate* introduces, not
    BLAS portability."""
    from p2p_tpu.models import TINY
    from p2p_tpu.parallel import sweep
    from tests.test_golden import _pipe
    from tests.test_phase_cache import (
        GATE, PLATFORM_TOL, STEPS, _sweep_inputs)

    # Reuse the test's exact input builder — the tool must measure the
    # same trajectory the golden-pinning test pins, or a drift regression
    # could pass one surface and fail the other.
    pipe = _pipe(TINY)
    ctx, lats, ctrls = _sweep_inputs(pipe)
    _, lat_base = sweep(pipe, ctx, lats, ctrls, num_steps=STEPS)
    _, lat_gate = sweep(pipe, ctx, lats, ctrls, num_steps=STEPS, gate=GATE)
    lat_base = np.asarray(lat_base, np.float64)
    golden = np.load(os.path.join(_REPO, "tests", "golden",
                                  "phase_gate.npz"))["latents_base"]
    ref = golden.astype(np.float64)
    if ((lat_base - ref) ** 2).mean() > PLATFORM_TOL:
        ref = lat_base
    d = np.asarray(lat_gate, np.float64) - ref
    return float((d ** 2).mean()), float(np.abs(d).max())


def _schedule_check():
    """The reuse-schedule leg (ISSUE 15), default-on — re-validates the
    COMMITTED search artifact (tools/schedules/default_v1.json) end to
    end:

    1. **golden drift** — the artifact resolved on the rehearsal workload
       (the exact trajectory the phase-gate golden pins) must stay inside
       the ≤1e-2 latent-MSE budget, with the same foreign-platform
       fallback as the phase_gate leg;
    2. **uniform parity** — a request whose schedule is the UNIFORM table
       must serve byte-identically to the equivalent ``gate=g`` request
       (and derive the identical compile key): the generalization's
       bitwise contract at the serving surface;
    3. **contracts** — the no-f64 and hot-scan-callback jaxpr contracts
       over the scheduled canonical programs (monolith + both pools).

    Returns (mse, speedup_recorded, uniform_bitwise, keys_pooled,
    contract_failures)."""
    import json

    import jax

    from p2p_tpu.engine.sampler import text2image
    from p2p_tpu.models import TINY
    from p2p_tpu.parallel import sweep
    from p2p_tpu.serve import Request, serve_forever
    from p2p_tpu.serve.request import prepare
    from tests.test_golden import _pipe
    from tests.test_phase_cache import PLATFORM_TOL, STEPS, _sweep_inputs

    art_path = os.path.join(_REPO, "tools", "schedules", "default_v1.json")
    with open(art_path) as f:
        spec = json.load(f)

    pipe = _pipe(TINY)
    ctx, lats, ctrls = _sweep_inputs(pipe)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _, lat_base = sweep(pipe, ctx, lats, ctrls, num_steps=STEPS)
        _, lat_sched = sweep(pipe, ctx, lats, ctrls, num_steps=STEPS,
                             schedule=spec)
    lat_base = np.asarray(lat_base, np.float64)
    golden = np.load(os.path.join(_REPO, "tests", "golden",
                                  "phase_gate.npz"))["latents_base"]
    ref = golden.astype(np.float64)
    if ((lat_base - ref) ** 2).mean() > PLATFORM_TOL:
        ref = lat_base
    mse = float(((np.asarray(lat_sched, np.float64) - ref) ** 2).mean())

    # Uniform-schedule serve leg: bitwise + key-pooled with plain gate=g.
    steps, seed = 3, 42
    prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]
    gate_req = Request(request_id="uni-gate", prompt=prompts[0],
                      target=prompts[1], mode="replace", steps=steps,
                      seed=seed, gate=0.5)
    uni_req = Request(request_id="uni-sched", prompt=prompts[0],
                      target=prompts[1], mode="replace", steps=steps,
                      seed=seed, schedule={"cfg_gate": 0.5})
    keys_pooled = (prepare(gate_req, pipe).compile_key
                   == prepare(uni_req, pipe).compile_key)
    imgs = {}
    for req in (gate_req, uni_req):
        recs = [r for r in serve_forever(pipe, [req], max_batch=4,
                                         max_wait_ms=1.0)
                if r["status"] == "ok"]
        assert len(recs) == 1, f"{req.request_id}: {len(recs)} ok records"
        imgs[req.request_id] = recs[0]["images"]
    uniform_bitwise = np.array_equal(imgs["uni-gate"], imgs["uni-sched"])

    # Contracts over the scheduled canonical programs.
    from p2p_tpu.analysis import contracts

    progs = contracts.scheduled_programs(spec=spec)
    results = (contracts.check_no_f64(progs)
               + contracts.check_hot_scan_callbacks(progs))
    fails = [r for r in results if not r.ok]
    speedup = (spec.get("provenance") or {}).get("measured_speedup")
    return mse, speedup, uniform_bitwise, keys_pooled, fails, len(results)


def _serve_parity():
    """max|Δ| between golden edits served through the full request path
    (queue → batcher → program cache → sweep) and the same specs run
    directly through ``text2image`` — the serving layer's
    numerics-neutrality contract (ISSUE 2): batching, padding and program
    caching must be bitwise-invisible. The controller is built through the
    same shared factory (``cli.controller_from_opts``) on both sides, so
    the only variable is the serving machinery itself.

    Two legs: the ungated single-lane case (the historical contract), and
    a GATED request that crosses the phase-disaggregated hand-off
    (ISSUE 6) — phase-1 pool → carry → phase-2 pool must reproduce direct
    gated ``text2image`` bitwise too."""
    import jax

    from p2p_tpu.cli import controller_from_opts
    from p2p_tpu.engine.sampler import text2image
    from p2p_tpu.models import TINY
    from p2p_tpu.serve import Request, serve_forever
    from tests.test_golden import _pipe

    pipe = _pipe(TINY)
    steps, seed = 3, 42
    prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]
    ctrl = controller_from_opts(prompts, pipe.tokenizer, steps,
                                mode="replace", cross_steps=0.8,
                                self_steps=0.4)
    worst = 0
    for name, gate in (("golden", None), ("golden-gated", 0.5)):
        req = Request(request_id=name, prompt=prompts[0], target=prompts[1],
                      mode="replace", steps=steps, seed=seed, gate=gate)
        recs = [r for r in serve_forever(pipe, [req], max_batch=4,
                                         max_wait_ms=1.0)
                if r["status"] == "ok"]
        assert len(recs) == 1, f"serve path produced {len(recs)} ok records"
        if gate is not None:
            assert "phases" in recs[0], "gated request skipped the pools"
        want, _, _ = text2image(pipe, prompts, ctrl, num_steps=steps,
                                rng=jax.random.PRNGKey(seed), gate=gate)
        d = np.abs(recs[0]["images"].astype(np.int16)
                   - np.asarray(want).astype(np.int16))
        worst = max(worst, int(d.max()))
    return worst


def _kernel_parity():
    """The fused-kernel numerics contract (ISSUE 16): interpret-mode fused
    attention (``KernelConfig(interpret=True)``) vs the reference
    ``attention_probs`` materialized path, end to end through
    ``text2image`` on the seeded tiny config.

    Legs:

    1. **non-edit bitwise** — with no controller every site takes the
       library flash path whether or not a KernelConfig rides the call, so
       images and latents must be bit-identical: the dispatch layer itself
       is program-invisible.
    2. **per edit family** — replace / refine / reweight controllers
       (store=False so every touched site actually fuses), plus a gated
       store=True run that exercises the *store* (phase-1 flash side
       output) and *use* (phase-2 cached maps) variants. Each family runs
       fused vs materialized; latent MSE must stay inside the drift
       budget. A static ``site_variant`` census per family guards against
       the leg going vacuous (zero fused sites would pass trivially).

    Observed parity on the pinning host is exactly 0.0 for every family
    (the kernel reproduces softmax→edit→PV in f32), so the default budget
    has orders-of-magnitude headroom."""
    import jax

    from p2p_tpu.align.words import get_equalizer
    from p2p_tpu.controllers import factory
    from p2p_tpu.engine.sampler import text2image
    from p2p_tpu.kernels import KernelConfig
    from p2p_tpu.kernels.dispatch import VARIANT_FUSED, site_variant
    from p2p_tpu.models import TINY
    from p2p_tpu.models.config import unet_layout
    from tests.test_golden import _pipe

    pipe = _pipe(TINY)
    tok = pipe.tokenizer
    steps, seed = 3, 42
    prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]
    kc = KernelConfig(interpret=True)
    layout = unet_layout(TINY.unet)
    rng = jax.random.PRNGKey(seed)

    def run(ctrl, gate=None, kernels=None):
        with warnings.catch_warnings():
            # The gated store+use family intentionally gates inside the
            # controller's edit window; the truncation advisory is expected.
            warnings.simplefilter("ignore", UserWarning)
            img, xt, _ = text2image(pipe, prompts, ctrl, num_steps=steps,
                                    rng=rng, gate=gate, kernels=kernels)
        return (np.asarray(img).astype(np.int16),
                np.asarray(xt, dtype=np.float64))

    img0, xt0 = run(None)
    img1, xt1 = run(None, kernels=kc)
    bitwise = bool(np.array_equal(img0, img1) and np.array_equal(xt0, xt1))

    size = pipe.config.unet.sample_size
    kw = dict(tokenizer=tok, max_len=pipe.config.text.max_length,
              self_max_pixels=size * size)
    eq = get_equalizer(prompts[0], ["burger"], [3.0], tok, mode="paired")
    families = {
        "replace": (factory.attention_replace(
            prompts, steps, 0.8, 0.4, store=False, **kw), None),
        "refine": (factory.attention_refine(
            prompts, steps, 0.8, 0.4, store=False, **kw), None),
        "reweight": (factory.attention_reweight(
            prompts, steps, 0.8, 0.4, eq, store=False, **kw), None),
        "store+use": (factory.attention_replace(
            prompts, steps, 0.8, 0.4, store=True, **kw), 0.5),
    }
    results = {}
    for name, (ctrl, gate) in families.items():
        fused_sites = sum(
            1 for m in layout.metas
            if site_variant(kc, ctrl, m, "off") == VARIANT_FUSED)
        img_r, xt_r = run(ctrl, gate=gate)
        img_f, xt_f = run(ctrl, gate=gate, kernels=kc)
        mse = float(((xt_f - xt_r) ** 2).mean())
        mx = int(np.abs(img_f - img_r).max())
        results[name] = (fused_sites, mse, mx)
    return bitwise, results


def _mesh_parity():
    """The mesh-parallel serving contract (ISSUE 10), two legs on the
    virtual 8-device mesh:

    1. **dp=1 bitwise** — ``--mesh dp=1`` must be bitwise-identical to the
       mesh-less engine: record stream byte-for-byte (zero-timer, images
       and the summary's mesh block stripped) and images bit-for-bit. The
       one-device mesh still takes the sharded staging/dispatch path, so
       this pins the whole mesh machinery as numerics-neutral.
    2. **gated dp=4 chaos drill** — the standard seeded gate-mix drill
       (faults, cancels, crash-replay) through a dp=4 mesh, unchanged:
       exactly-once terminals, ok-outputs bitwise-identical to the
       fault-free mesh run, hand-offs actually crossing the sharded
       pools. Durability must be mesh-agnostic — the drill's journal
       carries no topology, so this leg runs ``run_drill`` verbatim with
       only ``serve_kw={"mesh": ...}`` added.

    Returns (records_identical, images_identical, dp4_ok, handoffs,
    resumed)."""
    import importlib.util
    import json

    import jax
    import numpy as np

    from p2p_tpu.models import TINY
    from p2p_tpu.serve import MeshSpec, Request, serve_forever
    from tests.test_golden import _pipe

    pipe = _pipe(TINY)
    prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]
    reqs = [Request(request_id="mp-gated", prompt=prompts[0],
                    target=prompts[1], mode="replace", steps=3, seed=42,
                    gate=0.5, arrival_ms=0.0),
            Request(request_id="mp-plain", prompt=prompts[0], steps=3,
                    seed=7, arrival_ms=1.0)]

    def run(mesh):
        recs = list(serve_forever(pipe, list(reqs), max_batch=4,
                                  max_wait_ms=1.0, timer=lambda: 0.0,
                                  mesh=mesh))
        imgs = {r["request_id"]: r["images"] for r in recs
                if r["status"] == "ok"}
        stripped = [{k: v for k, v in r.items()
                     if k not in ("images", "mesh")} for r in recs]
        return json.dumps(stripped, sort_keys=True), imgs

    base_bytes, base_imgs = run(None)
    dp1_bytes, dp1_imgs = run(MeshSpec(dp=1))
    records_identical = base_bytes == dp1_bytes
    images_identical = (set(base_imgs) == set(dp1_imgs) and all(
        np.array_equal(base_imgs[k], dp1_imgs[k]) for k in base_imgs))

    # dp4_ok None = leg skipped (the operator pinned XLA_FLAGS to a
    # smaller virtual platform, so the file-top 8-device default never
    # applied): not a drift — the gate's own default environment always
    # runs it.
    dp4_ok, handoffs, resumed = None, 0, 0
    if len(jax.devices()) >= 4:
        spec = importlib.util.spec_from_file_location(
            "p2p_chaos_drill", os.path.join(_REPO, "tools",
                                            "chaos_drill.py"))
        drill = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(drill)
        gtrace, gplan = drill.standard_trace(gate_mix="0.5:3,off:1")
        res = drill.run_drill(drill.tiny_pipeline(), gtrace, gplan,
                              crash_after=8, warmup=True,
                              serve_kw={"mesh": MeshSpec(dp=4)})
        handoffs = res.get("handoffs", 0)
        resumed = res["crash_replay"]["resumed_handoffs"]
        dp4_ok = (handoffs > 0 and res["bitwise_compared"] > 0
                  and res["crash_replay"]["skipped_corrupt"] == 0)
    return records_identical, images_identical, dp4_ok, handoffs, resumed


def _fault_drill():
    """The resilience contract (ISSUE 4), gated on the standard seeded
    chaos drill (tools/chaos_drill.py, seed 8): a fixed loadgen trace under
    a fixed fault plan must (1) resolve every admitted request to exactly
    one terminal state, (2) keep every ``ok`` output bitwise-identical to
    the fault-free run of the same trace, and (3) survive a simulated
    crash + journaled restart with exactly-once semantics and zero corrupt
    records. ``run_drill`` raises on (1)/(2)/the crash invariant; the
    returned summary lets the gate also insist the drill actually *drilled*
    (faults fired, retries happened, the replay had pending work) — a plan
    that silently injects nothing would otherwise pass vacuously."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "p2p_chaos_drill", os.path.join(_REPO, "tools", "chaos_drill.py"))
    drill = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(drill)

    pipe = drill.tiny_pipeline()
    trace, plan = drill.standard_trace()
    res = drill.run_drill(pipe, trace, plan, crash_after=8, warmup=True)
    # The gated leg (ISSUE 6): the same seeded drill over a gate-mix trace,
    # so faults, cancellations and the crash-replay land on requests that
    # cross the two-pool hand-off — exactly-once and bitwise-stable must
    # hold through it (the deterministic mid-hand-off crash case itself is
    # pinned by tests/test_handoff.py).
    gtrace, gplan = drill.standard_trace(gate_mix="0.5:3,off:1")
    res["gated"] = drill.run_drill(pipe, gtrace, gplan, crash_after=8,
                                   warmup=True)
    return res


def _flight_parity():
    """The flight-tracing neutrality contract (ISSUE 7): serving the same
    trace with a FlightTracer attached must leave (1) every output image
    bitwise identical and (2) the serve JSONL record stream byte-identical
    to the tracer-off run — tracing is a sidecar, never a behavior change —
    while (3) producing one flight record per terminal whose gated causal
    chain covers admission → phase-1 dispatch → hand-off → phase-2
    dispatch → terminal and whose stage attribution sums to the recorded
    total. Returns (records_identical, images_identical, n_flights,
    n_attr_ok, gated_chain_ok)."""
    import json

    import numpy as np

    from p2p_tpu.obs.flight import FlightTracer
    from p2p_tpu.serve import Request, serve_forever
    from tests.test_golden import _pipe
    from p2p_tpu.models import TINY

    pipe = _pipe(TINY)
    prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]
    reqs = [Request(request_id="fp-gated", prompt=prompts[0],
                    target=prompts[1], mode="replace", steps=3, seed=42,
                    gate=0.5, arrival_ms=0.0),
            Request(request_id="fp-plain", prompt=prompts[0], steps=3,
                    seed=7, arrival_ms=1.0)]

    def run(tracer):
        # Deterministic timer: both runs measure identical (zero) wall
        # durations, so the byte-compare isolates the tracer's effect on
        # the record stream instead of cross-run timing noise. Outputs
        # still come from the real runners.
        recs = list(serve_forever(pipe, list(reqs), max_batch=4,
                                  max_wait_ms=1.0, timer=lambda: 0.0,
                                  flight=tracer))
        imgs = {r["request_id"]: r["images"] for r in recs
                if r["status"] == "ok"}
        stripped = [{k: v for k, v in r.items() if k != "images"}
                    for r in recs]
        return json.dumps(stripped, sort_keys=True), imgs

    base_bytes, base_imgs = run(None)
    tracer = FlightTracer()
    on_bytes, on_imgs = run(tracer)
    records_identical = base_bytes == on_bytes
    images_identical = (set(base_imgs) == set(on_imgs) and all(
        np.array_equal(base_imgs[k], on_imgs[k]) for k in base_imgs))
    oks = [r for r in tracer.records if r["status"] == "ok"]
    n_attr_ok = sum(1 for r in oks if r.get("attribution_ok"))
    gated = [r for r in tracer.records if r["request_id"] == "fp-gated"]
    chain_ok = False
    if gated:
        g = gated[0]
        stages = [(s["stage"], s.get("pool")) for s in g["segments"]]
        kinds = [e["kind"] for e in g["events"]]
        chain_ok = (kinds[0] == "admitted" and "handoff" in kinds
                    and kinds[-1] == "terminal"
                    and ("run", "phase1") in stages
                    and ("handoff_wait", "phase2") in stages
                    and ("run", "phase2") in stages
                    and g.get("attribution_ok") is True)
    return (records_identical, images_identical, len(tracer.records),
            n_attr_ok, chain_ok)


def _profile_parity(overhead_bound: float):
    """The production-profiling neutrality contract (ISSUE 18): serving
    the same trace with a ProdScope attached must leave (1) every output
    image bitwise identical, (2) the serve JSONL record stream
    byte-identical once the summary record's ``profile`` block is
    stripped (the only record addition the profiler is allowed), and
    (3) the journal byte-identical once the profiler's own
    ``profile_drift`` EVENT lines are stripped (the only journal
    addition the profiler is allowed) — while (4) capturing at
    least one sampled device trace, (5) writing a ledger that validates
    against the WorkloadProfile schema, and (6) keeping the recorded
    capture overhead under ``overhead_bound`` percent. Returns
    (records_identical, images_identical, journal_identical, captures,
    schema_problems, overhead_pct)."""
    import json
    import tempfile

    import numpy as np

    from p2p_tpu.obs import metrics as obs_metrics
    from p2p_tpu.obs import prodscope as obs_prodscope
    from p2p_tpu.obs import traceparse
    from p2p_tpu.serve import Journal, Request, serve_forever
    from tests.test_golden import _pipe
    from p2p_tpu.models import TINY

    pipe = _pipe(TINY)
    prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]
    reqs = [Request(request_id="pp-gated", prompt=prompts[0],
                    target=prompts[1], mode="replace", steps=3, seed=42,
                    gate=0.5, arrival_ms=0.0),
            Request(request_id="pp-plain", prompt=prompts[0], steps=3,
                    seed=7, arrival_ms=1.0)]

    def run(tmp, scope):
        # Deterministic timer (the flight_parity discipline): the
        # byte-compare isolates the profiler's effect on the record
        # stream, not cross-run timing noise.
        obs_metrics.registry().reset()
        jpath = os.path.join(tmp, "journal.jsonl")
        journal = Journal(jpath)
        try:
            recs = list(serve_forever(pipe, list(reqs), max_batch=4,
                                      max_wait_ms=1.0, timer=lambda: 0.0,
                                      journal=journal, prodscope=scope))
        finally:
            journal.close()
        imgs = {r["request_id"]: r["images"] for r in recs
                if r["status"] == "ok"}
        # The summary record's "profile" block is the one record
        # addition the profiler is allowed; everything else must match.
        stripped = [{k: v for k, v in r.items()
                     if k not in ("images", "profile")} for r in recs]
        with open(jpath) as f:
            # Carry-spill paths embed the per-run journal directory;
            # normalize so the byte-compare sees only real divergence.
            jlines = [ln.replace(tmp, "<TMP>") for ln in f]
        return json.dumps(stripped, sort_keys=True), imgs, jlines, recs[-1]

    with tempfile.TemporaryDirectory() as t_off, \
            tempfile.TemporaryDirectory() as t_on:
        base_bytes, base_imgs, base_j, _ = run(t_off, None)
        # period=1: every dispatch sampled — this tiny trace has too few
        # dispatches for a sparse plan to be guaranteed a capture.
        scope = obs_prodscope.ProdScope(os.path.join(t_on, "profile"),
                                        seed=0, period=1,
                                        tags={"preset": "tiny"})
        on_bytes, on_imgs, on_j, summary = run(t_on, scope)
        ledger = scope.ledger()

    records_identical = base_bytes == on_bytes
    images_identical = (set(base_imgs) == set(on_imgs) and all(
        np.array_equal(base_imgs[k], on_imgs[k]) for k in base_imgs))
    # The profiler's one permitted journal addition: profile_drift EVENT
    # lines (none expected at this scale — the sentinels' min_samples
    # suppresses short-run noise — but stripped defensively).
    on_j = [ln for ln in on_j if '"profile_drift"' not in ln]
    journal_identical = base_j == on_j
    prof = summary.get("profile", {})
    problems = traceparse.validate_profile(ledger)
    return (records_identical, images_identical, journal_identical,
            int(prof.get("captures", 0)), problems,
            float(prof.get("overhead_pct", 0.0)))


def _lifecycle():
    """The lifecycle-durability contract (ISSUE 9), gated on the chaos
    drill's rolling-restart leg: a deterministic (zero-timer) seeded
    gate-mix trace served through 4 cycles = 3 drain/restart boundaries —
    journal snapshot + compaction at each drain, a chaos
    ``kill_during_drain`` in the middle cycle — must produce exactly-once
    terminals, ok-outputs bitwise-identical to the uninterrupted run,
    snapshot+tail folds byte-equivalent to the never-compacted shadow
    WAL, and restarts that replay strictly fewer WAL records than the
    full history. ``rolling_restart_drill`` raises on any violation; the
    returned facts let the gate insist the drill actually drilled."""
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "p2p_chaos_drill", os.path.join(_REPO, "tools", "chaos_drill.py"))
    drill = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(drill)

    pipe = drill.tiny_pipeline()
    trace, _ = drill.standard_trace(n=24, seed=8, steps=4, fault_rate=0.0,
                                    cancel_rate=0.0, gate_mix="0.5:3,off:1")
    jpath = os.path.join(tempfile.mkdtemp(prefix="p2p-lifecycle-"),
                         "rolling.wal")
    return drill.rolling_restart_drill(
        pipe, trace, jpath, cycles=4, kill_mid_drain=True,
        serve_kw={"timer": lambda: 0.0})


def _slo():
    """The SLO-tiered scheduling contract (ISSUE 12), two halves:

    1. **Policy** — the deterministic virtual-clock overload drill
       (``chaos_drill.slo_overload_drill``): a seeded tenant/tier-mixed
       trace at 2× the engine's service capacity must shed best-effort
       ONLY, hold premium p99 within 1.2× of its uncontended p99, and
       resolve every request exactly once (quota rejections, preemptions
       and sheds included). The drill raises on any violation.
    2. **Durability** — ``chaos_drill.preempt_kill_drill``: a chaos
       ``preempt_then_kill`` parks a gated request's carry (journaled
       ``preempted`` record) and dies before the resume; the restart
       must resume it off the spill exactly-once with bitwise-identical
       output (real runners, real spills)."""
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "p2p_chaos_drill", os.path.join(_REPO, "tools", "chaos_drill.py"))
    drill = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(drill)

    pipe = drill.tiny_pipeline()
    policy = drill.slo_overload_drill(pipe)
    jpath = os.path.join(tempfile.mkdtemp(prefix="p2p-slo-"), "preempt.wal")
    durability = drill.preempt_kill_drill(pipe, jpath)
    return policy, durability


def _cache_parity():
    """The semantic-caching contract (ISSUE 13), two halves:

    1. **Parity + coverage** — ``chaos_drill.cache_parity_drill``: a
       seeded ``--zipf 1.1`` repeat-heavy gated trace served cached vs
       uncached must be bitwise-identical on every ok output (the drill
       raises otherwise) with ≥30% of requests served from cache and at
       least one hit in EVERY layer (L1 encoder outputs, L2 carry
       prefixes — exercised via real L3 evictions under a tight byte
       budget — and L3 exact results).
    2. **Durability** — ``chaos_drill.cache_insert_kill_drill``: a chaos
       ``kill_after_cache_insert`` dies between the leader's L3 insert
       and its terminal fsync; the restart must reseed off the journaled
       ``cache`` record and serve leader + followers from the durable
       insert, exactly-once, bitwise."""
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "p2p_chaos_drill", os.path.join(_REPO, "tools", "chaos_drill.py"))
    drill = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(drill)

    pipe = drill.tiny_pipeline()
    parity = drill.cache_parity_drill(pipe)
    jpath = os.path.join(tempfile.mkdtemp(prefix="p2p-cache-"), "cache.wal")
    durability = drill.cache_insert_kill_drill(pipe, jpath)
    return parity, durability


def _elastic():
    """The elastic-mesh serving contract (ISSUE 19), two halves:

    1. **Neutrality** — the off path must carry zero elastic artifacts:
       serving a deterministic trace without ``elastic`` must register no
       ``serve_resizes_total`` family and journal no ``resize`` records,
       and serving the SAME trace with an armed-but-idle controller
       (unreachable thresholds, dp=1) must keep every ok output bitwise
       identical to the mesh-less run, the record stream byte-identical
       once the summary's ``mesh``/``elastic`` blocks are stripped (the
       only record additions elastic is allowed), and the journal
       byte-identical (an idle controller never writes one). Runs BEFORE
       the drill so the family-absence assertion sees a registry the
       elastic path has never touched.
    2. **Resize drill** — ``chaos_drill.elastic_resize_drill``: a seeded
       diurnal trace must scale up ≥2× and down ≥2× with zero dropped
       requests, ok outputs within the documented ±1 vmap tolerance of a
       fixed-topology run, and a ``kill_during_resize`` crash that
       replays exactly-once, bitwise, resuming on the WAL's target
       topology. The drill raises on any violation; the returned facts
       let the gate insist it actually resized.

    Returns ``(facts, neutral)``; ``facts`` is None when the host
    exposes <4 devices (the drill needs dp=4 headroom)."""
    import importlib.util
    import json
    import tempfile

    import jax
    import numpy as np

    from p2p_tpu.obs import metrics as obs_metrics
    from p2p_tpu.serve import (ElasticConfig, Journal, Request,
                               serve_forever)

    spec = importlib.util.spec_from_file_location(
        "p2p_chaos_drill", os.path.join(_REPO, "tools", "chaos_drill.py"))
    drill = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(drill)

    pipe = drill.tiny_pipeline()
    prompts = ["a squirrel eating a burger", "a squirrel eating a lasagna"]
    reqs = [Request(request_id="el-gated", prompt=prompts[0],
                    target=prompts[1], mode="replace", steps=3, seed=42,
                    gate=0.5, arrival_ms=0.0),
            Request(request_id="el-plain", prompt=prompts[0], steps=3,
                    seed=7, arrival_ms=1.0)]

    def run(tmp, elastic):
        obs_metrics.registry().reset()
        jpath = os.path.join(tmp, "journal.jsonl")
        journal = Journal(jpath)
        try:
            recs = list(serve_forever(pipe, list(reqs), max_batch=4,
                                      max_wait_ms=1.0, timer=lambda: 0.0,
                                      journal=journal, elastic=elastic))
        finally:
            journal.close()
        imgs = {r["request_id"]: r["images"] for r in recs
                if r["status"] == "ok"}
        # The summary's "mesh"/"elastic" blocks are the record additions
        # elastic is allowed; everything else must match the off path.
        stripped = [{k: v for k, v in r.items()
                     if k not in ("images", "mesh", "elastic")}
                    for r in recs]
        with open(jpath) as f:
            jlines = [ln.replace(tmp, "<TMP>") for ln in f]
        return json.dumps(stripped, sort_keys=True), imgs, jlines

    with tempfile.TemporaryDirectory() as t_off, \
            tempfile.TemporaryDirectory() as t_idle:
        off_bytes, off_imgs, off_j = run(t_off, None)
        no_off_family = (
            obs_metrics.registry().get("serve_resizes_total") is None)
        # Unreachable up threshold; dp=1 cannot shrink below min_dp, so
        # the controller is armed but never fires — pure idle overhead.
        idle_bytes, idle_imgs, idle_j = run(
            t_idle, ElasticConfig(up_depth=1 << 20))
    neutral = {
        "records_identical": off_bytes == idle_bytes,
        "images_identical": (set(off_imgs) == set(idle_imgs) and all(
            np.array_equal(off_imgs[k], idle_imgs[k]) for k in off_imgs)),
        "journal_identical": off_j == idle_j,
        "no_off_family": no_off_family,
        "no_resize_records": not any('"resize"' in ln
                                     for ln in off_j + idle_j),
    }

    if len(jax.devices()) < 4:
        return None, neutral
    jpath = os.path.join(tempfile.mkdtemp(prefix="p2p-elastic-"),
                         "elastic.wal")
    return drill.elastic_resize_drill(pipe, jpath), neutral


def _soak():
    """The opt-in long-horizon soak rehearsal (ISSUE 9 acceptance): ≥500
    virtual-clock-served requests across ≥5 snapshot/compact/restart
    cycles with WAL+spill disk bounded by a constant, zero fd/thread
    leaks, bounded RSS growth, and attribution-exact flight records at
    every cycle. Fake-runner volume drill (tools/soak.py) — the real-
    runner correctness half is the default ``lifecycle`` check."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "p2p_soak", os.path.join(_REPO, "tools", "soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    spec2 = importlib.util.spec_from_file_location(
        "p2p_chaos_drill", os.path.join(_REPO, "tools", "chaos_drill.py"))
    drill = importlib.util.module_from_spec(spec2)
    spec2.loader.exec_module(drill)
    pipe = drill.tiny_pipeline()
    return soak.run_soak(
        pipe, cycles=6, duration_ms=30000.0, rate_per_s=20.0, seed=0,
        steps=4, snapshot_every_ms=4000.0, drain_timeout_ms=60.0,
        min_requests=500, min_cycles=5,
        progress=lambda msg: print("  " + msg))


def _obs_overhead(reps=4):
    """(overhead_frac, bitwise_identical, step_events) for the telemetry
    path (ISSUE 3): the same tiny sampling run with metrics enabled (step
    callbacks traced in, host collector installed) vs disabled.

    The contract this gates: enabling telemetry is numerics-neutral
    (bitwise-identical images — callbacks are a pure side channel) and its
    wall-clock cost stays inside a bound. Disabled-mode program identity is
    pinned structurally by tests/test_obs.py's jaxpr check; here the
    enabled path pays for itself. Timing discipline for a noisy shared CPU:
    the two variants are timed *interleaved* (off/on pairs, so load drift
    hits both sides) and each side takes its best-of-``reps`` — measured
    ~16% on an idle host, but ~80% has been observed under a concurrently
    running test suite, which is why the default bound is a
    pathology-catcher, not a precision target (the bench ``obs`` block
    records the per-round number on the round's own hardware)."""
    import jax

    from p2p_tpu.engine.sampler import text2image
    from p2p_tpu.models import TINY
    from p2p_tpu.obs import device as obs_device
    from p2p_tpu.obs import metrics as obs_metrics
    from tests.test_golden import _pipe

    pipe = _pipe(TINY)
    prompts = ["a squirrel eating a burger"]

    def run(metrics):
        img, _, _ = text2image(pipe, prompts, None, num_steps=4,
                               rng=jax.random.PRNGKey(3), metrics=metrics)
        return np.asarray(img)

    base = run(False)   # also the compile pass for the plain program
    obs_metrics.registry().reset()
    with obs_device.instrument():
        inst = run(True)  # compile pass for the instrumented program
        identical = bool(np.array_equal(base, inst))
        t_on, t_off = [], []
        for _ in range(reps):
            t_off.append(_timed(run, False))
            t_on.append(_timed(run, True))
    t_on, t_off = min(t_on), min(t_off)
    snap = obs_metrics.registry().snapshot()
    steps = sum(s["value"] for s in
                snap.get("sampler_steps_total", {"samples": []})["samples"])
    overhead = max(0.0, t_on / t_off - 1.0)
    return overhead, identical, int(steps)


def _timed(run, metrics):
    t0 = time.perf_counter()
    run(metrics)
    return time.perf_counter() - t0


def _static_analysis():
    """The jaxcheck report (ISSUE 5 + ISSUE 11): every analyzer pass —
    AST lints against the committed baseline, traced-program contracts
    (no f64, no hot-scan callbacks, phase-2 footprint,
    donation-as-declared), the compile-key completeness sweep over the
    full Request schema, and the shardcheck pass (declared collectives /
    no hidden resharding / no host boundary over the compiled mesh serve
    programs). The gate fails on any NEW lint finding
    (suppressed/baselined don't count) or any contract/field/shardcheck
    violation — the same verdict ``python tools/jaxcheck.py`` exits on.
    One bucket and one mesh width (dp=2: the narrowest non-degenerate
    mesh) keep the in-gate run fast; the bucket and dp axes are swept by
    the analyzer CLI and its own tests."""
    from p2p_tpu.analysis import report as report_mod

    # The cost pass runs as the gate's own `cost_regression` leg (below),
    # so the canonical programs compile once per gate run, not twice.
    report = report_mod.run_all(buckets=(1,), collective_dps=(2,),
                                sections=("ast", "contracts",
                                          "collectives"))
    new = report["ast"]["summary"]["new"]
    contract_fails = [r for r in report["contracts"]["results"] if not r.ok]
    # Compile-key and content-key sweeps share the verdict line: both are
    # per-field completeness checks over the same Request schema (program
    # identity and output identity respectively — ISSUE 13).
    key_fails = [v for v in (report["compile_key"]["fields"]
                             + report["content_key"]["fields"]) if not v.ok]
    shard_fails = [r for r in report["collectives"]["results"] if not r.ok]
    shard_bytes = sum(row["bytes_per_step"]
                      for row in report["collectives"]["table"].values())
    detail = []
    for f in report["ast"]["findings"]:
        if f.is_new:
            detail.append("  " + f.format())
    detail += ["  " + r.format() for r in contract_fails]
    detail += ["  " + v.format() for v in key_fails]
    detail += ["  " + r.format() for r in shard_fails]
    return (report["ok"], new, len(report["contracts"]["results"]),
            len(contract_fails),
            len(report["compile_key"]["fields"])
            + len(report["content_key"]["fields"]),
            len(key_fails), len(report["collectives"]["results"]),
            len(shard_fails), shard_bytes, detail)


def _wal_protocol():
    """The WAL protocol checker (ISSUE 20) as its own default-on leg —
    pass 5 is jax-free and runs apart from ``static_analysis`` so the WAL
    verdict survives a traced-pass environment problem (and vice versa).
    Fails on any completeness-sweep error, any model-check invariant
    violation or coverage gap, or any seeded bug that no longer flips
    (a checker gone blind is itself a regression)."""
    from p2p_tpu.analysis import report as report_mod

    section = report_mod.run_wal_pass()["wal"]
    sweep = section["protocol"]
    model = section["model"]
    flips = section["seeded"]
    detail = ["  " + v.format() for v in sweep if not v.ok]
    detail += [f"  {v['invariant']} at {v['point']} ({v['window']}) of "
               f"[{v['trace']}]: {v['detail']}"
               for v in model["violations"]]
    for missing, what in ((model["kinds_missing"], "record/event kind(s)"),
                          (model["windows_missing"], "crash window(s)")):
        if missing:
            detail.append(f"  coverage: {what} never exercised: {missing}")
    detail += [f"  seeded bug {f['bug']} DOES NOT FLIP" for f in flips
               if not f["flipped"]]
    return (section["ok"], len(sweep),
            sum(1 for v in sweep if not v.ok), model["crash_points"],
            len(model["violations"]),
            sum(1 for f in flips if f["flipped"]), len(flips), detail)


def _cost_regression(pipe, budgets_path=None):
    """The cost-observatory budget contract (ISSUE 14): compile the
    canonical serve programs, extract their XLA cost cards
    (``obs.costmodel``) and diff the frozen fields (flops, bytes
    accessed) against ``tools/cost_budgets.json``. A refactor that
    silently doubles a canonical program's bytes accessed fails here *by
    program name* — the same frozen-artifact discipline jaxcheck applies
    to compile keys and collectives. Returns the verdict list."""
    from p2p_tpu.obs import costmodel

    cards = costmodel.canonical_cost_cards(pipe)
    budgets = costmodel.load_budgets(
        budgets_path or os.path.join(_REPO, costmodel.DEFAULT_BUDGETS))
    return costmodel.check_budgets(cards, budgets)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of golden configs")
    ap.add_argument("--mse", type=float, default=0.25,
                    help="max image MSE (uint8² units) per config")
    ap.add_argument("--max-abs", type=float, default=3.0,
                    help="max per-pixel abs diff (uint8 steps) per config")
    ap.add_argument("--gate-mse", type=float, default=1e-2,
                    help="max gate=0.5T latent MSE vs the pinned ungated "
                         "latents (ISSUE 1 drift contract)")
    ap.add_argument("--skip-gate", action="store_true",
                    help="skip the phase-gate drift check")
    ap.add_argument("--skip-schedule", action="store_true",
                    help="skip the reuse-schedule check (ISSUE 15; ~40s: "
                         "committed-artifact drift vs the golden budget, "
                         "uniform-schedule serve parity bitwise vs gate, "
                         "jaxcheck contracts on scheduled canonical "
                         "programs)")
    ap.add_argument("--skip-serve", action="store_true",
                    help="skip the serve-path parity check")
    ap.add_argument("--serve-max-abs", type=int, default=0,
                    help="max per-pixel abs diff for the serve-path parity "
                         "check (default 0: serving must be bitwise "
                         "numerics-neutral)")
    ap.add_argument("--skip-kernel", action="store_true",
                    help="skip the fused-kernel parity leg (interpret-mode "
                         "fused attention vs the materialized reference "
                         "path, per edit family)")
    ap.add_argument("--kernel-mse", type=float, default=1e-6,
                    metavar="B",
                    help="latent-MSE budget per edit family for the "
                         "kernel_parity leg (default %(default)s; observed "
                         "parity is exactly 0.0 on the pinning host)")
    ap.add_argument("--skip-obs", action="store_true",
                    help="skip the telemetry-overhead check")
    ap.add_argument("--skip-mesh", action="store_true",
                    help="skip the mesh-parallel serving parity check "
                         "(ISSUE 10; ~45s: dp=1 bitwise leg + the gated "
                         "dp=4 chaos drill on the virtual 8-device mesh)")
    ap.add_argument("--skip-flight", action="store_true",
                    help="skip the flight-tracing parity check (ISSUE 7)")
    ap.add_argument("--skip-profile", action="store_true",
                    help="skip the production-profiling parity check "
                         "(ISSUE 18; ~15s: serves the 2-request gated "
                         "trace with and without a ProdScope at "
                         "period=1 and byte-compares records, images "
                         "and journal)")
    ap.add_argument("--profile-overhead-bound", type=float, default=5000.0,
                    metavar="PCT",
                    help="max recorded capture overhead_pct for the "
                         "profile_parity leg (default %(default)s). A "
                         "pathology-catcher, not a precision target: the "
                         "leg samples EVERY dispatch of a 3-step tiny-CPU "
                         "trace, so trace start/stop + parse dwarfs the "
                         "sub-ms device work (~1000%% observed); a real "
                         "deployment samples 1/N of multi-second "
                         "dispatches. The bench 'serve.profile' block "
                         "records the trustworthy per-round number")
    ap.add_argument("--bench-trend", action="store_true",
                    help="also run the opt-in bench_trend check: diff the "
                         "latest committed BENCH_r*.json round against its "
                         "like-for-like predecessor on the headline keys "
                         "(tools/benchwatch.py) and fail past "
                         "--bench-trend-threshold")
    ap.add_argument("--bench-trend-threshold", type=float, default=0.10,
                    help="regression budget for --bench-trend (fraction; "
                         "default 0.10)")
    ap.add_argument("--skip-fault-drill", action="store_true",
                    help="skip the chaos/crash-replay resilience check "
                         "(ISSUE 4; ~35s: it serves the standard trace "
                         "four times)")
    ap.add_argument("--skip-lifecycle", action="store_true",
                    help="skip the rolling-restart lifecycle check "
                         "(ISSUE 9; ~30s: 3 drain/restart cycles over a "
                         "gated trace, real runners)")
    ap.add_argument("--skip-slo", action="store_true",
                    help="skip the SLO-tiered scheduling check (ISSUE 12; "
                         "~20s: the virtual-clock 2x-overload policy "
                         "drill + the preempt_then_kill durability "
                         "drill)")
    ap.add_argument("--skip-cache", action="store_true",
                    help="skip the semantic-caching check (ISSUE 13; "
                         "~30s: the zipf cached-vs-uncached parity drill "
                         "+ the kill_after_cache_insert durability drill)")
    ap.add_argument("--skip-elastic", action="store_true",
                    help="skip the elastic-mesh serving check (ISSUE 19; "
                         "~2min: off-path neutrality byte-compare + the "
                         "diurnal resize drill with kill_during_resize "
                         "durability)")
    ap.add_argument("--soak", action="store_true",
                    help="also run the opt-in soak rehearsal (ISSUE 9): "
                         "≥500 requests across ≥5 snapshot/compact/"
                         "restart cycles with bounded disk/RSS/fd/thread "
                         "invariants (fake runners, ~1 min); also "
                         "reachable as --only soak")
    ap.add_argument("--skip-cost", action="store_true",
                    help="skip the cost_regression check (ISSUE 14; "
                         "~20s: compile the canonical serve programs and "
                         "diff their XLA cost cards against the frozen "
                         "tools/cost_budgets.json)")
    ap.add_argument("--cost-budgets", default=None, metavar="FILE",
                    help="budgets file for cost_regression (default: "
                         "tools/cost_budgets.json; the override exists "
                         "so the verdict-flip drill can gate against a "
                         "perturbed copy)")
    ap.add_argument("--skip-static", action="store_true",
                    help="skip the static-analysis check (ISSUE 5 + 11; "
                         "~90s: AST lints + traced-program contracts + "
                         "the compile-key completeness sweep + the "
                         "shardcheck collective-budget pass at dp=2)")
    ap.add_argument("--skip-wal", action="store_true",
                    help="skip the WAL protocol checker leg (ISSUE 20; "
                         "~15s, jax-free: the declared-protocol "
                         "completeness sweep + the exhaustive small-scope "
                         "crash model check + the seeded verdict-flips)")
    ap.add_argument("--obs-overhead", type=float, default=1.5,
                    help="max fractional wall-clock overhead of the "
                         "metrics-enabled sampler vs disabled (ISSUE 3 "
                         "bound). A pathology-catcher, not a precision "
                         "target: ~0.16 idle but ~0.8 observed on a "
                         "contended CI host, while a real regression "
                         "(e.g. accidentally synchronous callbacks) is "
                         "10×+ — the bench 'obs' block records the "
                         "trustworthy per-round number")
    args = ap.parse_args(argv)

    cases, golden_dir, pipe = _cases()
    only = set(args.only.split(",")) if args.only else None
    if only:
        unknown = only - set(cases) - {"phase_gate", "serve_parity",
                                       "obs_overhead", "fault_drill",
                                       "static_analysis", "flight_parity",
                                       "bench_trend", "lifecycle", "soak",
                                       "mesh_parity", "slo", "cache_parity",
                                       "cost_regression", "schedule",
                                       "kernel_parity", "profile_parity",
                                       "elastic", "wal_protocol"}
        if unknown:
            ap.error(f"unknown config(s) {sorted(unknown)}; "
                     f"valid: {', '.join(cases)}, phase_gate, serve_parity, "
                     f"obs_overhead, fault_drill, static_analysis, "
                     f"flight_parity, bench_trend, lifecycle, soak, "
                     f"mesh_parity, slo, cache_parity, cost_regression, "
                     f"schedule, kernel_parity, profile_parity, elastic, "
                     f"wal_protocol")

    drifted = []
    for name, fn in cases.items():
        if only and name not in only:
            continue
        path = os.path.join(golden_dir, f"{name}.npz")
        if not os.path.exists(path):
            print(f"{name:16s} MISSING golden array at {path}")
            drifted.append(name)
            continue
        img = np.asarray(fn(pipe)).astype(np.int16)
        ref = np.load(path)["image"].astype(np.int16)
        if img.shape != ref.shape:
            print(f"{name:16s} SHAPE {img.shape} vs golden {ref.shape}")
            drifted.append(name)
            continue
        d = np.abs(img - ref)
        mse = float((d.astype(np.float64) ** 2).mean())
        ok = mse <= args.mse and d.max() <= args.max_abs
        print(f"{name:16s} mse={mse:.4g} max|Δ|={int(d.max())} "
              f"{'ok' if ok else 'DRIFT'}")
        if not ok:
            drifted.append(name)

    if not args.skip_gate and (only is None or "phase_gate" in only):
        mse, mx = _phase_gate_drift()
        ok = mse <= args.gate_mse
        print(f"{'phase_gate':16s} latent mse={mse:.4g} max|Δ|={mx:.3g} "
              f"{'ok' if ok else 'DRIFT'}")
        if not ok:
            drifted.append("phase_gate")

    if not args.skip_schedule and (only is None or "schedule" in only):
        mse, speedup, bitwise, pooled, fails, n_contracts = \
            _schedule_check()
        ok = (mse <= args.gate_mse and bitwise and pooled and not fails)
        print(f"{'schedule':16s} artifact mse={mse:.4g} "
              f"(recorded speedup {speedup}x), uniform-schedule serve "
              f"{'bitwise' if bitwise else 'DIFF'}, keys "
              f"{'pooled' if pooled else 'SPLIT'}, "
              f"{n_contracts - len(fails)}/{n_contracts} scheduled "
              f"contracts {'ok' if ok else 'DRIFT'}")
        for r in fails:
            print("  " + r.format())
        if not ok:
            drifted.append("schedule")

    if not args.skip_serve and (only is None or "serve_parity" in only):
        mx = _serve_parity()
        ok = mx <= args.serve_max_abs
        print(f"{'serve_parity':16s} max|Δ|={mx} vs direct text2image "
              f"{'ok' if ok else 'DRIFT'}")
        if not ok:
            drifted.append("serve_parity")

    if not args.skip_kernel and (only is None or "kernel_parity" in only):
        bitwise, fam = _kernel_parity()
        vacuous = [n for n, (sites, _, _) in fam.items() if sites == 0]
        worst = max(mse for _, mse, _ in fam.values())
        ok = bitwise and not vacuous and worst <= args.kernel_mse
        detail = ", ".join(f"{n}: {sites} fused mse={mse:.3g} "
                           f"max|Δ|={mx}" for n, (sites, mse, mx)
                           in fam.items())
        print(f"{'kernel_parity':16s} non-edit "
              f"{'bitwise' if bitwise else 'DIFF'}; {detail} "
              f"{'ok' if ok else 'DRIFT'}")
        if vacuous:
            print(f"  vacuous families (0 fused sites): {vacuous}")
        if not ok:
            drifted.append("kernel_parity")

    if not args.skip_flight and (only is None or "flight_parity" in only):
        rec_id, img_id, n_flights, n_attr, chain = _flight_parity()
        ok = rec_id and img_id and n_flights == 2 and n_attr == 2 and chain
        print(f"{'flight_parity':16s} records "
              f"{'byte-identical' if rec_id else 'DIFF'}, images "
              f"{'bitwise' if img_id else 'DIFF'}, {n_flights} flight "
              f"record(s), {n_attr} attribution-exact, gated chain "
              f"{'covered' if chain else 'BROKEN'} "
              f"{'ok' if ok else 'DRIFT'}")
        if not ok:
            drifted.append("flight_parity")

    if not args.skip_profile and (only is None or "profile_parity" in only):
        (rec_id, img_id, j_id, captures, problems,
         overhead) = _profile_parity(args.profile_overhead_bound)
        ok = (rec_id and img_id and j_id and captures >= 1
              and not problems and overhead <= args.profile_overhead_bound)
        print(f"{'profile_parity':16s} records "
              f"{'byte-identical' if rec_id else 'DIFF'}, images "
              f"{'bitwise' if img_id else 'DIFF'}, journal "
              f"{'byte-identical' if j_id else 'DIFF'}, {captures} "
              f"capture(s), schema "
              f"{'clean' if not problems else problems}, "
              f"overhead +{overhead:.0f}% {'ok' if ok else 'DRIFT'}")
        if not ok:
            drifted.append("profile_parity")

    if args.bench_trend or (only is not None and "bench_trend" in only):
        # Opt-in: the committed BENCH trajectory is only diffable when the
        # latest round has a like-for-like predecessor, and most gate runs
        # happen mid-round — so the trend watch runs on request, not by
        # default.
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "p2p_benchwatch", os.path.join(_REPO, "tools", "benchwatch.py"))
        benchwatch = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(benchwatch)
        report = benchwatch.watch(_REPO, args.bench_trend_threshold)
        print(benchwatch.render(report))
        if report["regressions"]:
            drifted.append("bench_trend")

    if not args.skip_mesh and (only is None or "mesh_parity" in only):
        try:
            rec_id, img_id, dp4_ok, handoffs, resumed = _mesh_parity()
        except AssertionError as e:  # DrillFailure in the dp=4 leg
            print(f"{'mesh_parity':16s} INVARIANT VIOLATED: {e}")
            drifted.append("mesh_parity")
        else:
            ok = rec_id and img_id and dp4_ok is not False
            dp4_txt = ("skipped (<4 devices on this platform)"
                       if dp4_ok is None else
                       f"{handoffs} hand-offs, {resumed} resumed")
            print(f"{'mesh_parity':16s} dp=1 records "
                  f"{'byte-identical' if rec_id else 'DIFF'}, images "
                  f"{'bitwise' if img_id else 'DIFF'}; dp=4 chaos drill "
                  f"{dp4_txt} {'ok' if ok else 'DRIFT'}")
            if not ok:
                drifted.append("mesh_parity")

    if not args.skip_obs and (only is None or "obs_overhead" in only):
        overhead, identical, steps = _obs_overhead()
        ok = overhead <= args.obs_overhead and identical and steps > 0
        print(f"{'obs_overhead':16s} +{overhead * 100:.1f}% vs disabled, "
              f"bitwise={'ok' if identical else 'DIFF'}, "
              f"step_events={steps} {'ok' if ok else 'DRIFT'}")
        if not ok:
            drifted.append("obs_overhead")

    if not args.skip_fault_drill and (only is None or "fault_drill" in only):
        try:
            res = _fault_drill()
        except AssertionError as e:  # DrillFailure: an invariant broke
            print(f"{'fault_drill':16s} INVARIANT VIOLATED: {e}")
            drifted.append("fault_drill")
        else:
            fired = sum(res["faults"].values())
            replay = res["crash_replay"]
            gated = res["gated"]
            ok = (res["bitwise_compared"] > 0 and fired > 0
                  and res["retries"] > 0 and replay["replayed_pending"] > 0
                  and replay["skipped_corrupt"] == 0
                  # The gated leg must actually cross the hand-off and
                  # hold the same invariants (run_drill raised otherwise).
                  and gated["bitwise_compared"] > 0
                  and gated.get("handoffs", 0) > 0
                  and gated["crash_replay"]["skipped_corrupt"] == 0)
            print(f"{'fault_drill':16s} {fired} faults fired, "
                  f"{res['retries']} retries, "
                  f"{res['bitwise_compared']} ok outputs bitwise-stable, "
                  f"replay {replay['replayed_pending']} pending/"
                  f"{replay['already_terminal']} terminal; gated leg "
                  f"{gated.get('handoffs', 0)} hand-offs, "
                  f"{gated['bitwise_compared']} bitwise, "
                  f"{gated['crash_replay']['resumed_handoffs']} resumed "
                  f"{'ok' if ok else 'DRIFT'}")
            if not ok:
                drifted.append("fault_drill")

    if not args.skip_lifecycle and (only is None or "lifecycle" in only):
        try:
            res = _lifecycle()
        except AssertionError as e:  # DrillFailure: an invariant broke
            print(f"{'lifecycle':16s} INVARIANT VIOLATED: {e}")
            drifted.append("lifecycle")
        else:
            tails = res["restart_tail_records"]
            ok = (res["cycles"] == 4 and res["completed_drains"] >= 2
                  and res["kills"] == 1 and res["bitwise_compared"] > 0
                  # Every restart after a completed drain replayed a tail
                  # strictly smaller than the full history (the drill
                  # raises otherwise; insist it measured something).
                  and len(tails) == res["cycles"] - 1
                  and res["full_history_records"] > max(tails))
            print(f"{'lifecycle':16s} {res['completed_drains']} drains + "
                  f"{res['kills']} mid-drain kill over {res['cycles']} "
                  f"cycles, {res['bitwise_compared']} ok outputs bitwise, "
                  f"restart tails {tails} vs {res['full_history_records']} "
                  f"full-history records {'ok' if ok else 'DRIFT'}")
            if not ok:
                drifted.append("lifecycle")

    if not args.skip_slo and (only is None or "slo" in only):
        try:
            policy, durability = _slo()
        except AssertionError as e:  # DrillFailure: an invariant broke
            print(f"{'slo':16s} INVARIANT VIOLATED: {e}")
            drifted.append("slo")
        else:
            ok = (policy["premium_p99_ratio"] <= 1.2
                  and policy["best_effort_shed"] > 0
                  and policy["paid_shed"] == 0
                  and policy["preemptions"] > 0
                  and policy["quota_rejects"] > 0
                  and durability["resumed_handoffs"] >= 1
                  and durability["bitwise_compared"] > 0
                  and durability["replay_skipped_corrupt"] == 0)
            print(f"{'slo':16s} premium p99 "
                  f"{policy['premium_p99_ratio']:.3f}x uncontended, "
                  f"{policy['best_effort_shed']} best-effort shed / "
                  f"{policy['paid_shed']} paid, "
                  f"{policy['preemptions']} preemptions, "
                  f"{policy['quota_rejects']} quota rejects; "
                  f"preempt+kill {durability['resumed_handoffs']} resumed, "
                  f"{durability['bitwise_compared']} bitwise "
                  f"{'ok' if ok else 'DRIFT'}")
            if not ok:
                drifted.append("slo")

    if not args.skip_cache and (only is None or "cache_parity" in only):
        try:
            parity, durability = _cache_parity()
        except AssertionError as e:  # DrillFailure: an invariant broke
            print(f"{'cache_parity':16s} INVARIANT VIOLATED: {e}")
            drifted.append("cache_parity")
        else:
            ok = (parity["served_from_cache_fraction"] >= 0.3
                  and parity["l1_hits"] >= 1
                  and parity["l2_hits"] >= 1
                  and parity["l3_hits"] >= 1
                  and parity["l3_evictions"] >= 1
                  and durability["killed"]
                  and durability["followers_bitwise"] == 2
                  and durability["restart_served_from_cache"] >= 1
                  and durability["replay_skipped_corrupt"] == 0)
            print(f"{'cache_parity':16s} "
                  f"{parity['served_from_cache_fraction'] * 100:.0f}% "
                  f"served from cache (l1/l2/l3 hits "
                  f"{parity['l1_hits']}/{parity['l2_hits']}/"
                  f"{parity['l3_hits']}, {parity['l3_evictions']} "
                  f"evictions), {parity['amplification']}x amplification, "
                  f"all ok outputs bitwise; insert-kill restart served "
                  f"{durability['restart_served_from_cache']} from the "
                  f"durable insert {'ok' if ok else 'DRIFT'}")
            if not ok:
                drifted.append("cache_parity")

    if not args.skip_elastic and (only is None or "elastic" in only):
        try:
            res, neutral = _elastic()
        except AssertionError as e:  # DrillFailure: an invariant broke
            print(f"{'elastic':16s} INVARIANT VIOLATED: {e}")
            drifted.append("elastic")
        else:
            neutral_ok = all(neutral.values())
            if res is None:
                import jax
                ok = neutral_ok
                print(f"{'elastic':16s} off-path neutral "
                      f"{'ok' if neutral_ok else 'DRIFT'}; resize drill "
                      f"skipped (<4 devices: {len(jax.devices())})")
            else:
                ok = (neutral_ok
                      and res["resizes_up"] >= 2
                      and res["resizes_down"] >= 2
                      and res["dropped"] == 0
                      and res["parity_compared"] > 0
                      and res["parity_max_abs"] <= 1
                      and res["prewarm_ms"] > 0
                      and res["kill"]["killed"]
                      and res["kill"]["restart_dp"] == 2
                      and res["kill"]["resumed_handoffs"] >= 1
                      and res["kill"]["bitwise_compared"] > 0
                      and res["kill"]["replay_skipped_corrupt"] == 0)
                bad = sorted(k for k, v in neutral.items() if not v)
                print(f"{'elastic':16s} "
                      f"{res['resizes_up']} up / {res['resizes_down']} "
                      f"down resizes, {res['dropped']} dropped, parity "
                      f"max|Δ|={res['parity_max_abs']} over "
                      f"{res['parity_compared']}, kill restart on dp="
                      f"{res['kill']['restart_dp']} resumed "
                      f"{res['kill']['resumed_handoffs']}, off-path "
                      + (f"NEUTRALITY DRIFT {bad}" if bad else "neutral")
                      + f" {'ok' if ok else 'DRIFT'}")
            if not ok:
                drifted.append("elastic")

    if args.soak or (only is not None and "soak" in only):
        # Opt-in volume rehearsal — minutes of fake-runner traffic; the
        # default lifecycle check already covers correctness.
        try:
            res = _soak()
        except AssertionError as e:
            print(f"{'soak':16s} INVARIANT VIOLATED: {e}")
            drifted.append("soak")
        else:
            print(f"{'soak':16s} {res['requests_served']} requests / "
                  f"{res['cycles']} cycles, disk ≤ "
                  f"{max(res['disk_bytes_per_cycle'])}B, rss +"
                  f"{res['rss_growth_kb']}kB, {res['snapshots_total']} "
                  f"snapshots ok")

    if not args.skip_cost and (only is None or "cost_regression" in only):
        verdicts = _cost_regression(pipe, budgets_path=args.cost_budgets)
        bad = [v for v in verdicts if not v.ok]
        names = sorted({v.program for v in bad})
        print(f"{'cost_regression':16s} {len(bad)}/{len(verdicts)} frozen "
              f"cost-budget violation(s)"
              + (f" in {', '.join(names)}" if names else "")
              + f" {'ok' if not bad else 'DRIFT'}")
        for v in bad:
            print("  " + v.format())
        if bad:
            drifted.append("cost_regression")

    if not args.skip_static and (only is None or "static_analysis" in only):
        (ok, new, n_contracts, bad_contracts, n_fields, bad_fields,
         n_shard, bad_shard, shard_bytes, detail) = _static_analysis()
        print(f"{'static_analysis':16s} {new} new lint finding(s), "
              f"{bad_contracts}/{n_contracts} contract failure(s), "
              f"{bad_fields}/{n_fields} compile-key violation(s), "
              f"{bad_shard}/{n_shard} shardcheck failure(s) "
              f"({shard_bytes}B/step collective budget) "
              f"{'ok' if ok else 'DRIFT'}")
        for line in detail:
            print(line)
        if not ok:
            drifted.append("static_analysis")

    if not args.skip_wal and (only is None or "wal_protocol" in only):
        (ok, n_sweep, bad_sweep, crash_points, n_viol, flipped, n_bugs,
         detail) = _wal_protocol()
        print(f"{'wal_protocol':16s} {bad_sweep}/{n_sweep} protocol sweep "
              f"failure(s), {n_viol} violation(s) across {crash_points} "
              f"model-checked crash point(s), {flipped}/{n_bugs} seeded "
              f"bug(s) flip {'ok' if ok else 'DRIFT'}")
        for line in detail:
            print(line)
        if not ok:
            drifted.append("wal_protocol")

    if drifted:
        print(f"QUALITY GATE FAILED: {', '.join(drifted)} "
              "(regenerate goldens only for intentional numerics changes: "
              "P2P_REGEN_GOLDEN=1 pytest tests/test_golden.py)")
        return 1
    print("quality gate passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
