"""jaxcheck — the repo's static analyzer (docs/STATIC_ANALYSIS.md).

Five passes over the stack, one exit code:

    python tools/jaxcheck.py                  # all passes, full report
    python tools/jaxcheck.py --ast-only       # milliseconds: lints only
    python tools/jaxcheck.py --only collectives  # just the shardcheck pass
    python tools/jaxcheck.py --only cost      # cost cards vs frozen budgets
    python tools/jaxcheck.py --only wal       # WAL protocol + crash model
    python tools/jaxcheck.py --json out.json  # structured report for CI
    python tools/jaxcheck.py --fix            # mechanical fixes in place
    python tools/jaxcheck.py --update-baseline  # accept current findings
    python tools/jaxcheck.py p2p_tpu/serve    # narrow the lint targets

Exit codes: 0 = clean (new findings: none; contracts: all hold),
1 = violations, 2 = usage error. ``p2p-tpu check --static`` and the
``static_analysis`` check in tools/quality_gate.py run the same passes
through ``p2p_tpu.analysis``.

``--fix`` is best-effort and mechanical only (dead-import removal,
suppression-comment normalization): it re-lints after rewriting and
refuses any rewrite that would introduce a finding. Semantic findings
(traced branches, host syncs, mutable defaults) always need a human.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# AST-only runs must stay jax-free and instant; the contract pass forces
# CPU before its first jax import (same scrub as the test conftest).
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="lint targets (files/dirs, default: the package + "
                         "tool drivers)")
    ap.add_argument("--ast-only", action="store_true",
                    help="skip the traced-program passes (no jax import; "
                         "milliseconds) — shorthand for --only ast")
    ap.add_argument("--only", default=None,
                    choices=("ast", "contracts", "collectives", "cost",
                             "wal"),
                    help="run a single report section: 'ast' (pass 1), "
                         "'contracts' (jaxpr contracts + compile-key "
                         "sweep), 'collectives' (the shardcheck pass "
                         "alone — fast local iteration on mesh programs), "
                         "'cost' (the cost observatory's canonical "
                         "cards vs the frozen tools/cost_budgets.json), "
                         "or 'wal' (pass 5: the WAL protocol sweep + the "
                         "exhaustive crash model check — jax-free)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: tools/"
                         "jaxcheck_baseline.json; '' disables)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to accept every current "
                         "(unsuppressed) AST finding")
    ap.add_argument("--json", default=None, metavar="FILE",
                    help="also write the structured report here")
    ap.add_argument("--fix", action="store_true",
                    help="apply mechanical fixes (unused imports, "
                         "suppression formatting) to the lint targets, "
                         "then re-run")
    ap.add_argument("--buckets", default="1,2,4,8",
                    help="serve lane buckets the contract pass traces "
                         "(comma list; fewer = faster)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print passing checks and non-new findings")
    args = ap.parse_args(argv)

    if args.update_baseline and args.baseline == "":
        # '' means "no baseline in use" — rewriting the committed default
        # from a de-baselined run would be the opposite of what was asked.
        ap.error("--update-baseline conflicts with --baseline '' "
                 "(baselining disabled); name the file to write")
    if args.ast_only and args.only not in (None, "ast"):
        ap.error(f"--ast-only conflicts with --only {args.only}")
    if args.ast_only:
        args.only = "ast"
    if args.update_baseline and args.only not in (None, "ast"):
        # The baseline is AST-pass state; accepting it from a run that
        # never lints would silently wipe the file.
        ap.error("--update-baseline needs the AST pass (drop --only, or "
                 "use --only ast)")
    if args.paths and args.only in ("contracts", "collectives", "cost",
                                    "wal"):
        # Honored-flags discipline: lint targets would be silently unread.
        ap.error(f"lint targets only apply to the AST pass; "
                 f"--only {args.only} takes none")
    if args.fix and args.only in ("contracts", "collectives", "cost",
                                  "wal"):
        # --fix rewrites lint targets and re-lints them; a run that never
        # lints would rewrite files whose state the report never reflects.
        ap.error(f"--fix needs the AST pass (drop --only {args.only})")

    if args.only not in ("ast", "wal"):
        # The traced passes import jax: pin the deterministic CPU backend
        # first (the passes are structure checks, never device work), and
        # force the virtual 8-device platform (same helper as the other
        # drivers) so the sharded canonical programs and the shardcheck
        # dp ∈ {1,2,4} sweep run everywhere this driver does, not only
        # where an operator exported XLA_FLAGS.
        from p2p_tpu.utils.platform import force_cpu_platform

        force_cpu_platform()

    from p2p_tpu.analysis import report as report_mod

    paths = args.paths or None

    if args.fix:
        from p2p_tpu.analysis import fixes
        from p2p_tpu.analysis.astlint import iter_python_files

        targets = [p if os.path.isabs(p) else os.path.join(_REPO, p)
                   for p in (args.paths
                             or report_mod.DEFAULT_LINT_PATHS)]
        gone = [t for t in targets if not os.path.exists(t)]
        if gone:
            ap.error(f"--fix target(s) do not exist: {gone}")
        changed = 0
        for path in iter_python_files(targets):
            res = fixes.fix_file(path, repo_root=_REPO)
            if res.get("aborted"):
                print(f"fix skipped {res['path']}: {res['aborted']}")
            elif res["changed"]:
                changed += 1
                print(f"fixed {res['path']}: "
                      f"{res['unused_imports_removed']} import(s) removed, "
                      f"{res['suppressions_normalized']} suppression(s) "
                      "normalized")
        print(f"--fix rewrote {changed} file(s)")

    try:
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    except ValueError:
        ap.error(f"--buckets expects a comma list of ints, "
                 f"got {args.buckets!r}")

    try:
        report = report_mod.run_all(paths, baseline_path=args.baseline,
                                    only=args.only, buckets=buckets)
    except FileNotFoundError as e:
        ap.error(str(e))   # a typo'd target is a usage error (exit 2)

    if args.update_baseline:
        from p2p_tpu.analysis.findings import save_baseline

        baseline_path = (args.baseline if args.baseline is not None
                         else os.path.join(_REPO,
                                           report_mod.DEFAULT_BASELINE))
        save_baseline(baseline_path, report["ast"]["findings"])
        print(f"baseline updated: {baseline_path} "
              f"({report['ast']['summary']['new']} finding(s) accepted)")
        # Re-baseline the in-memory report so the exit code reflects the
        # file just written — AST section only: the traced/compiled
        # sections are baseline-independent, and re-running them would
        # re-trace (and re-compile) every canonical program for an
        # identical result.
        report["ast"] = report_mod.run_ast_pass(
            paths, baseline_path=baseline_path)
        oks = [report["ast"]["summary"]["new"] == 0]
        if "contracts" in report:
            oks += [report["contracts"]["ok"], report["compile_key"]["ok"],
                    report["content_key"]["ok"]]
        if "collectives" in report:
            oks.append(report["collectives"]["ok"])
        if "cost" in report:
            oks.append(report["cost"]["ok"])
        if "wal" in report:
            oks.append(report["wal"]["ok"])
        report["ok"] = all(oks)

    print(report_mod.render_text(report, verbose=args.verbose))
    if args.json:
        os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(report_mod.to_json_dict(report), f, indent=1)
        print(f"wrote {args.json}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
