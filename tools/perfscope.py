"""perfscope — the per-program cost/roofline observatory CLI (ISSUE 14).

The tool-derived form of the PERF.md arithmetic: XLA cost cards
(``cost_analysis``/``memory_analysis``) for every canonical program,
roofline classification and model-predicted ms against the per-platform
peak table, measured MFU, and the frozen-budget diff the quality gate's
``cost_regression`` leg enforces.

    python tools/perfscope.py                  # canonical cards + roofline
    python tools/perfscope.py --headline       # reproduce the PERF.md MFU
                                               # arithmetic from recorded
                                               # artifacts alone
    python tools/perfscope.py --check-budgets  # diff vs tools/cost_budgets
                                               # .json (the CI leg); exit 1
                                               # names drifted programs
    python tools/perfscope.py --update-budgets # freeze the current cards
                                               # (deliberate regeneration)
    python tools/perfscope.py --programs F     # render a serve
                                               # --programs-out artifact
    python tools/perfscope.py --sites TRACE --fuse-plan out.json
                                               # rank sites fuse-first
                                               # (share x map bytes) for
                                               # KernelConfig.from_fuse_plan;
                                               # TRACE may be a serve
                                               # --profile WorkloadProfile
                                               # ledger (measured ms x map
                                               # bytes scoring)
    python tools/perfscope.py --json out.json  # structured report

``--headline`` recomputes "89 TF/s ≈ 45% MFU at 40.75 ms/step" from the
committed artifacts only: per-step FLOPs + measured ms/step recorded in
``tools/cost_budgets.json``'s ``headline`` block (provenance: the round-5
on-chip ``cost_analysis()`` capture), peaks from the platform table —
no hand arithmetic anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def headline(budgets: dict) -> dict:
    """The PERF.md headline MFU arithmetic off recorded artifacts: per-step
    FLOPs and measured ms/step from the budgets' ``headline`` block,
    peaks from the platform table. Returns the derived numbers."""
    from p2p_tpu.obs import costmodel

    head = budgets["headline"]
    peaks = costmodel.lookup_peaks(head["platform"])
    if peaks is None:
        raise ValueError(f"no peak-table entry for platform "
                         f"{head['platform']!r}")
    flops = float(head["flops_per_step"])
    ms = float(head["measured_ms_per_step"])
    mfu = costmodel.mfu_pct(flops, ms, peaks)
    return {
        "program": head["program"],
        "platform": head["platform"],
        "flops_per_step": flops,
        "measured_ms_per_step": ms,
        "tf_per_s": flops / (ms / 1e3) / 1e12,
        "peak_tf_per_s": peaks.flops_per_s / 1e12,
        "mfu_pct": mfu,
        "predicted_ms_at_peak": flops / peaks.flops_per_s * 1e3,
        "source": head.get("source", ""),
    }


def render_headline(h: dict) -> str:
    return (f"{h['program']} on {h['platform']}: "
            f"{h['tf_per_s']:.1f} TF/s ≈ {h['mfu_pct']:.1f}% MFU "
            f"at {h['measured_ms_per_step']:.2f} ms/step "
            f"(peak {h['peak_tf_per_s']:.0f} TF/s; "
            f"{h['flops_per_step'] / 1e12:.2f} TF/step; "
            f"compute floor {h['predicted_ms_at_peak']:.1f} ms/step)")


def render_cards(cards: dict, peaks) -> str:
    from p2p_tpu.obs import costmodel

    lines = [f"peaks: {peaks.flops_per_s / 1e12:.3f} TF/s, "
             f"{peaks.bytes_per_s / 1e9:.2f} GB/s "
             f"({peaks.platform}, {peaks.source}; "
             f"ridge {peaks.ridge:.1f} flops/byte)",
             f"  {'program':26s} {'flops':>12s} {'bytes':>12s} "
             f"{'int.':>6s} {'bound':>9s} {'pred ms':>8s}"]
    for name in sorted(cards):
        c = cards[name]
        roof = costmodel.roofline(c["flops"], c["bytes_accessed"], peaks)
        lines.append(
            f"  {name:26s} {c['flops']:>12.4g} "
            f"{c['bytes_accessed']:>12.4g} "
            f"{roof['arith_intensity']:>6.2f} {roof['bound']:>9s} "
            f"{roof['predicted_ms']:>8.2f}")
    return "\n".join(lines)


# The named_scope trace parser moved to the shared module (ISSUE 18) so
# the serve engine's production profiler folds traces through the same
# code path; re-exported here for import compatibility.
from p2p_tpu.obs.traceparse import (  # noqa: E402
    parse_site_trace, parse_sites_any)


def fuse_plan(entries: list, config: str = "sd14",
              group_batch: int = 1, source: str = "trace") -> dict:
    """Rank attention sites fuse-first (ISSUE 16): measured step-time share
    (a ``--sites`` trace table) × the bytes the materialized probability
    map moves per step (``2B·heads·P·K·4``, the f32 softmax the fused-edit
    kernel keeps in VMEM). The product is the roofline-weighted payoff of
    fusing that site: a site that is both hot on the trace AND moves a big
    map fuses first. ``group_batch`` is B (prompts per edit group; the 2×
    is CFG). Sites the layout knows but the trace never measured rank last
    at share 0 (explicitly ``measured: false`` — taking the whole list
    still fuses them); trace sites unknown to ``config``'s layout are
    dropped LOUDLY in the returned ``dropped`` list, never silently.
    The emitted ``fuse_order`` is exactly what
    ``kernels.KernelConfig.from_fuse_plan`` consumes.

    With ``source="profile"`` (ISSUE 18: entries from a WorkloadProfile
    ledger, which carry absolute ``dur_us``) the score upgrades from
    relative share to measured ms × map bytes, and each ranked site
    records its ``measured_ms`` — same ordering semantics, better units.
    """
    from p2p_tpu.engine.reuse import site_name
    from p2p_tpu.models.config import PRESET_CONFIGS, unet_layout

    if config not in PRESET_CONFIGS:
        raise ValueError(f"unknown --plan-config {config!r} "
                         f"(one of {sorted(PRESET_CONFIGS)})")
    metas = {site_name(m): m
             for m in unet_layout(PRESET_CONFIGS[config].unet).metas}
    shares = {e["site"]: e["share"] for e in entries}
    durs = {e["site"]: e.get("dur_us") for e in entries}
    use_ms = source == "profile" and all(
        durs.get(s) is not None for s in shares)
    dropped = sorted(set(shares) - set(metas))
    order = []
    for name, m in metas.items():
        share = shares.get(name, 0.0)
        map_bytes = 2 * group_batch * m.heads * m.pixels * m.key_len * 4
        entry = {"site": name, "share": share,
                 "map_bytes": map_bytes,
                 "score": share * map_bytes,
                 "measured": name in shares}
        if use_ms:
            ms = (durs.get(name) or 0.0) / 1e3
            entry["measured_ms"] = ms
            entry["score"] = ms * map_bytes
        order.append(entry)
    order.sort(key=lambda d: (-d["score"], -d["map_bytes"]))
    return {"config": config, "group_batch": group_batch,
            "source": source if use_ms else "trace",
            "fuse_order": order, "dropped": dropped}


def render_fuse_plan(plan: dict) -> str:
    profiled = plan.get("source") == "profile"
    ms_col = f" {'meas ms':>8s}" if profiled else ""
    lines = [f"  {'site':22s} {'share':>7s} {'map MiB':>9s} "
             f"{'score':>10s}{ms_col}"]
    for e in plan["fuse_order"]:
        mark = "" if e["measured"] else "  (unmeasured)"
        ms = f" {e.get('measured_ms', 0.0):>8.3f}" if profiled else ""
        lines.append(f"  {e['site']:22s} {e['share'] * 100:>6.1f}% "
                     f"{e['map_bytes'] / 2**20:>9.2f} "
                     f"{e['score']:>10.3g}{ms}{mark}")
    if plan["dropped"]:
        lines.append(f"  dropped {len(plan['dropped'])} trace site(s) not "
                     f"in the {plan['config']!r} layout: "
                     f"{', '.join(plan['dropped'])}")
    return "\n".join(lines)


def render_sites(entries: list) -> str:
    lines = [f"  {'site':22s} {'dur ms':>10s} {'slices':>7s} {'share':>7s}"]
    for e in entries:
        lines.append(f"  {e['site']:22s} {e['dur_us'] / 1e3:>10.3f} "
                     f"{e['slices']:>7d} {e['share'] * 100:>6.1f}%")
    cross = sum(e["share"] for e in entries
                if e["site"].startswith("cross_attn/"))
    lines.append(f"  cross-attention share of attention time: "
                 f"{cross * 100:.1f}%")
    return "\n".join(lines)


def render_programs(entries: list) -> str:
    lines = [f"  {'program':40s} {'flops':>12s} {'bytes':>12s} "
             f"{'bound':>9s} {'pred ms':>8s} {'disp':>5s} "
             f"{'run ms':>8s} {'MFU%':>6s}"]
    for e in entries:
        mfu = e.get("mean_mfu_pct")
        lines.append(
            f"  {e['program'][:40]:40s} {e['flops']:>12.4g} "
            f"{e['bytes_accessed']:>12.4g} {e.get('bound', '?'):>9s} "
            f"{e.get('predicted_ms', 0.0):>8.2f} "
            f"{e.get('dispatches', 0):>5d} "
            f"{e.get('mean_run_ms', 0.0):>8.2f} "
            f"{'-' if mfu is None else f'{mfu:.1f}':>6s}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--headline", action="store_true",
                    help="reproduce the PERF.md headline MFU arithmetic "
                         "from the recorded artifacts alone")
    ap.add_argument("--check-budgets", action="store_true",
                    help="diff the canonical cost cards against the "
                         "frozen budgets; exit 1 naming drifted programs "
                         "(the quality-gate cost_regression leg)")
    ap.add_argument("--update-budgets", action="store_true",
                    help="rewrite the frozen canonical budgets from the "
                         "current cards (a DELIBERATE cost change, "
                         "reviewed like a golden regen)")
    ap.add_argument("--programs", default=None, metavar="FILE",
                    help="render a serve --programs-out JSONL artifact "
                         "instead of compiling the canonical programs")
    ap.add_argument("--sites", default=None, metavar="TRACE|PROFILE",
                    help="render the per-attention-site step-time share "
                         "table from a recorded Perfetto/chrome device "
                         "trace (named_scope site names) OR a serve "
                         "--profile WorkloadProfile ledger (auto-"
                         "detected by content) — the reuse-schedule "
                         "search's seed input "
                         "(tools/schedule_search.py --sites-json / "
                         "--profile)")
    ap.add_argument("--fuse-plan", default=None, metavar="FILE",
                    help="with --sites: write the ranked fuse-first site "
                         "list (measured step-time share × materialized-"
                         "map bytes) to FILE — the artifact "
                         "kernels.KernelConfig.from_fuse_plan consumes")
    ap.add_argument("--plan-config", default="sd14", metavar="NAME",
                    help="model preset whose attention layout prices the "
                         "--fuse-plan map bytes (default: sd14)")
    ap.add_argument("--group-batch", type=int, default=1, metavar="B",
                    help="prompts per edit group for the --fuse-plan map "
                         "bytes (the 2x CFG doubling is applied on top; "
                         "default: 1)")
    ap.add_argument("--budgets", default=None, metavar="FILE",
                    help="budgets file (default: tools/cost_budgets.json)")
    ap.add_argument("--json", default=None, metavar="FILE",
                    help="also write the structured report here")
    args = ap.parse_args(argv)

    # Honored-flags discipline (same as jaxcheck): a mode that would
    # silently ignore another requested action is a usage error, never a
    # quiet no-op — `--update-budgets --headline` must not print a
    # headline and leave the operator believing the budgets re-froze.
    if args.update_budgets and args.check_budgets:
        ap.error("--update-budgets conflicts with --check-budgets "
                 "(freeze or verify, not both)")
    if args.headline and (args.update_budgets or args.check_budgets):
        ap.error("--headline is a read-only report; it cannot run with "
                 "--update-budgets/--check-budgets")
    if args.programs and (args.headline or args.update_budgets
                          or args.check_budgets):
        ap.error("--programs renders a recorded artifact; it takes none "
                 "of --headline/--check-budgets/--update-budgets")
    if args.sites and (args.programs or args.headline
                       or args.update_budgets or args.check_budgets):
        ap.error("--sites renders a recorded trace; it takes none of "
                 "--programs/--headline/--check-budgets/--update-budgets")
    if args.fuse_plan and not args.sites:
        ap.error("--fuse-plan ranks measured sites; it needs --sites "
                 "TRACE (the recorded device trace to price)")

    report: dict = {}
    rc = 0

    if args.sites:
        try:
            entries, kind = parse_sites_any(args.sites)
        except (OSError, ValueError) as e:
            print(f"--sites: {e}", file=sys.stderr)
            return 2
        print(f"{len(entries)} attention site(s) from {args.sites} "
              f"({kind})")
        print(render_sites(entries))
        report["sites"] = entries
        report["sites_source"] = kind
        if args.fuse_plan:
            try:
                plan = fuse_plan(entries, config=args.plan_config,
                                 group_batch=args.group_batch,
                                 source=kind)
            except ValueError as e:
                print(f"--fuse-plan: {e}", file=sys.stderr)
                return 2
            print(render_fuse_plan(plan))
            os.makedirs(os.path.dirname(args.fuse_plan) or ".",
                        exist_ok=True)
            with open(args.fuse_plan, "w") as f:
                json.dump(plan, f, indent=2)
                f.write("\n")
            print(f"wrote fuse plan: {args.fuse_plan} "
                  f"({len(plan['fuse_order'])} site(s) ranked)")
            report["fuse_plan"] = plan
    elif args.programs:
        entries = []
        with open(args.programs) as f:
            for line in f:
                line = line.strip()
                if line:
                    entries.append(json.loads(line))
        print(f"{len(entries)} program cost card(s) from {args.programs}")
        print(render_programs(entries))
        report["programs"] = entries
    else:
        # Everything below needs the package; pin the deterministic CPU
        # backend exactly like the other analyzer drivers.
        from p2p_tpu.utils.platform import force_cpu_platform

        force_cpu_platform()

        from p2p_tpu.obs import costmodel

        budgets_path = args.budgets or os.path.join(
            _REPO, costmodel.DEFAULT_BUDGETS)
        budgets = costmodel.load_budgets(budgets_path)

        if args.headline:
            h = headline(budgets)
            print(render_headline(h))
            report["headline"] = h
        else:
            cards = costmodel.canonical_cost_cards()
            report["cards"] = cards
            peaks = costmodel.detect_peaks()
            report["peaks"] = peaks.to_dict()
            print(render_cards(cards, peaks))
            if args.update_budgets:
                budgets["programs"] = {
                    name: {f: cards[name][f]
                           for f in costmodel.BUDGET_FIELDS}
                    for name in sorted(cards)}
                with open(budgets_path, "w") as f:
                    json.dump(budgets, f, indent=2)
                    f.write("\n")
                print(f"budgets updated: {budgets_path} "
                      f"({len(cards)} program(s) frozen)")
            elif args.check_budgets:
                verdicts = costmodel.check_budgets(cards, budgets)
                bad = [v for v in verdicts if not v.ok]
                for v in verdicts:
                    if not v.ok:
                        print(v.format())
                report["budget"] = [v.to_dict() for v in verdicts]
                if bad:
                    names = sorted({v.program for v in bad})
                    print(f"COST REGRESSION: {', '.join(names)} "
                          f"(deliberate change? python tools/perfscope.py "
                          f"--update-budgets)")
                    rc = 1
                else:
                    print(f"cost budgets hold "
                          f"({len(verdicts)} check(s) clean)")

    if args.json:
        os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
        print(f"wrote {args.json}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
