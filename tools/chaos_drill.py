"""Deterministic chaos drill for the fault-tolerant serve loop.

Runs a seeded loadgen trace through ``p2p_tpu.serve.serve_forever`` twice —
once fault-free, once under a seeded ``FaultPlan`` — and asserts the two
drill invariants the fault-tolerance layer promises (ISSUE 4):

1. **Exactly one terminal state.** Every admitted request resolves to
   exactly one of ``ok / rejected / expired / timeout / error /
   invalid_output / cancelled / shed`` — under any fault plan, nothing is
   dropped and nothing is answered twice.
2. **Bitwise-stable outputs.** Every ``ok`` record in the faulted run is
   also ``ok`` in the fault-free run and its image is bitwise-identical:
   retries, lane isolation and warm-bucket re-dispatch may change *when* a
   request runs, never *what* it computes.

``--crash-after K`` adds the crash-replay drill: the first run is
abandoned after K terminal records (a simulated process death; the WAL
keeps only what was flushed), then the loop restarts against the same
``--journal`` file and the same trace — the invariant is that the union of
both runs serves every request exactly once, with no completed request
re-running.

``--rolling N`` adds the lifecycle leg (ISSUE 9): N graceful
drain/restart cycles mid-trace — each drain snapshots + compacts the
journal, each restart warm-resumes from snapshot + WAL tail — must yield
exactly-once terminals, ok-outputs bitwise-identical to the uninterrupted
run, snapshot+tail folds byte-equivalent to the never-compacted shadow
WAL, and restarts that replay *strictly fewer* records than the full
history (asserted, not just measured). ``--kill-mid-drain`` arms a chaos
``kill_during_drain`` in the middle cycle.

The whole drill is virtual-clock deterministic on the random-init tiny
pipeline (no checkpoints), so it doubles as the ``fault_drill`` check in
``tools/quality_gate.py`` and the ``resilience`` block in ``bench.py``.

    python tools/chaos_drill.py                      # standard drill
    python tools/chaos_drill.py --n 32 --fault-rate 0.4 --seed 7
    python tools/chaos_drill.py --crash-after 8      # + crash-replay drill
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _pin_cpu():
    """Deterministic CPU backend (same scrub as quality_gate: the drill's
    contract is bitwise, so the platform must be pinned). Called from
    ``main()`` only — importers like bench.py choose their own backend and
    must not have theirs scrubbed at import time."""
    from p2p_tpu.utils.cache import default_cache_dir

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          default_cache_dir(hash_xla_flags=False))


class DrillFailure(AssertionError):
    """An invariant the fault-tolerance layer promises did not hold."""


def tiny_pipeline():
    """Random-init TINY pipeline (the conftest fixture's standalone twin):
    drills need determinism, not checkpoints."""
    import jax

    from p2p_tpu.engine.sampler import Pipeline
    from p2p_tpu.models import TINY, init_text_encoder, init_unet
    from p2p_tpu.models import vae as vae_mod
    from p2p_tpu.utils.tokenizer import HashWordTokenizer

    return Pipeline(
        config=TINY,
        unet_params=init_unet(jax.random.PRNGKey(0), TINY.unet),
        text_params=init_text_encoder(jax.random.PRNGKey(1), TINY.text),
        vae_params=vae_mod.init_vae(jax.random.PRNGKey(2), TINY.vae),
        tokenizer=HashWordTokenizer(model_max_length=TINY.text.max_length),
    )


def standard_trace(n: int = 24, seed: int = 8, steps: int = 4,
                   fault_rate: float = 0.25, cancel_rate: float = 0.1,
                   kinds=("transient", "poison", "nan"),
                   gate_mix=None):
    """(trace, FaultPlan) pair for the standard drill — all seeded, so
    every caller (CLI, quality gate, bench) drills the identical scenario
    for the same arguments. ``gate_mix`` (a ``loadgen.parse_gate_mix``
    spec string) draws per-request phase gates, so the drill exercises the
    two-pool hand-off path; the default keeps the historical all-ungated
    trace byte-identical."""
    import importlib.util

    from p2p_tpu.serve.chaos import FaultPlan

    spec = importlib.util.spec_from_file_location(
        "p2p_loadgen", os.path.join(_REPO, "tools", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    trace = loadgen.generate_trace(
        n, mode="poisson", rate_per_s=50.0, seed=seed, steps=steps,
        gate_mix=(loadgen.parse_gate_mix(gate_mix) if gate_mix else None))
    plan = FaultPlan.from_dict(
        loadgen.fault_plan_dict(trace, seed, fault_rate, kinds=kinds))
    if cancel_rate > 0:
        trace = loadgen.with_cancels(trace, seed, cancel_rate)
    return trace, plan


def _terminal_records(records):
    from p2p_tpu.serve.engine_loop import TERMINAL_STATUSES

    return [r for r in records if r.get("status") in TERMINAL_STATUSES]


def check_exactly_once(trace, records, label: str = "drill") -> dict:
    """Invariant 1: every admitted request id → exactly one terminal
    record. Returns {id: record}."""
    ids = [r["request_id"] for r in trace if "request_id" in r]
    seen: dict = {}
    for rec in _terminal_records(records):
        rid = rec["request_id"]
        if rid in seen:
            raise DrillFailure(
                f"{label}: request {rid!r} resolved twice "
                f"({seen[rid]['status']} then {rec['status']})")
        seen[rid] = rec
    missing = [rid for rid in ids if rid not in seen]
    if missing:
        raise DrillFailure(f"{label}: {len(missing)} request(s) never "
                           f"reached a terminal state: {missing[:5]}")
    extra = set(seen) - set(ids)
    if extra:
        raise DrillFailure(f"{label}: terminal records for ids not in the "
                           f"trace: {sorted(extra)[:5]}")
    return seen


def check_bitwise_vs_clean(clean_by_id: dict, faulted_by_id: dict) -> int:
    """Invariant 2: every faulted-run ``ok`` is ``ok`` in the clean run
    with a bitwise-identical image. Returns how many ids were compared."""
    import numpy as np

    compared = 0
    for rid, rec in faulted_by_id.items():
        if rec["status"] != "ok":
            continue
        clean = clean_by_id.get(rid)
        if clean is None or clean["status"] != "ok":
            raise DrillFailure(
                f"request {rid!r} is ok under faults but "
                f"{clean['status'] if clean else 'missing'} fault-free — "
                "faults must only ever degrade, never manufacture results")
        if not np.array_equal(np.asarray(rec["images"]),
                              np.asarray(clean["images"])):
            raise DrillFailure(
                f"request {rid!r}: output under faults differs from the "
                "fault-free run — retries/isolation changed the numerics")
        compared += 1
    return compared


def _prewarm_reps(pipe, trace):
    """One representative request per distinct compile key — the
    bucket-pinning compile-ahead list (see the comment in run_drill)."""
    from p2p_tpu.serve import Request, prepare

    reps, seen = [], set()
    for d in trace:
        if "request_id" not in d:
            continue
        r = Request.from_dict(d)
        key = prepare(r, pipe).compile_key
        if key not in seen:
            seen.add(key)
            reps.append(r)
    return reps


def run_drill(pipe, trace, plan, *, watchdog_ms=None, journal_path=None,
              crash_after=None, serve_kw=None, warmup: bool = False) -> dict:
    """Run the (clean, faulted[, crash-replay]) drill; raise
    :class:`DrillFailure` on any invariant violation; return the
    resilience summary the bench/quality-gate callers record.

    ``warmup=True`` runs the clean trace once unmeasured first, so the
    measured runs both hit warm compile caches and the reported p95 delta
    is retry/backoff cost, not compile noise."""
    from p2p_tpu.serve import serve_forever

    # phase2_max_batch pinned to max_batch: the drill's bitwise invariant
    # compares clean vs faulted runs whose batch *composition* may differ
    # (wall-clock timing feeds the virtual clock). Padding within one
    # bucket is proven bitwise-invariant; different buckets are only
    # vmap-tolerance-equal — so the drill keeps every pool on one bucket.
    kw = dict(max_batch=4, max_wait_ms=20.0, queue_cap=256,
              validate_outputs=True, phase2_max_batch=4)
    kw.update(serve_kw or {})

    # Bucket-pinning compile-ahead (the PR-5-era "host-drift" resilience
    # flake, root-caused): flush boundaries are host-load-dependent, so
    # without prewarm a partial flush early in one run compiles (and
    # rides) a SMALLER bucket than the same requests hit in the other run
    # — and cross-bucket vmap widths only match to ±1, breaking the
    # bitwise invariant under contention. Warming every distinct compile
    # key at the max bucket makes warm-preference pad every dispatch
    # (full, partial, isolation re-run) to that one bucket, so outputs
    # are composition-independent — and it mirrors what the serve CLI
    # does by default (compile-ahead).
    if "prewarm" not in kw:
        kw["prewarm"] = _prewarm_reps(pipe, trace)

    if warmup:
        for _ in serve_forever(pipe, list(trace), **kw):
            pass
    clean = list(serve_forever(pipe, list(trace), **kw))
    clean_by_id = check_exactly_once(trace, clean, "fault-free run")

    plan.reset()
    faulted = list(serve_forever(pipe, list(trace), chaos=plan,
                                 watchdog_ms=watchdog_ms, **kw))
    faulted_by_id = check_exactly_once(trace, faulted, "faulted run")
    compared = check_bitwise_vs_clean(clean_by_id, faulted_by_id)

    def _counts(by_id):
        out: dict = {}
        for rec in by_id.values():
            out[rec["status"]] = out.get(rec["status"], 0) + 1
        return out

    clean_summary = clean[-1]
    faulted_summary = faulted[-1]
    result = {
        "n_requests": len(clean_by_id),
        "faults_planned": len(plan),
        "clean_counts": _counts(clean_by_id),
        "faulted_counts": _counts(faulted_by_id),
        "bitwise_compared": compared,
        "retries": faulted_summary["retries"],
        "faults": faulted_summary["faults"],
        "watchdog_timeouts": faulted_summary["watchdog_timeouts"],
        "shed": faulted_summary["counts"]["shed"],
        "p95_clean_ms": clean_summary["p95_ms"],
        "p95_faulted_ms": faulted_summary["p95_ms"],
        "p95_delta_ms": faulted_summary["p95_ms"] - clean_summary["p95_ms"],
    }
    if "phases" in faulted_summary:
        # Gate-mixed traces drill the two-pool hand-off path: surface how
        # much of the drill actually crossed it (a gated drill with zero
        # hand-offs would be vacuous).
        result["handoffs"] = faulted_summary["phases"]["handoffs"]

    if crash_after is not None:
        if journal_path is None:
            journal_path = os.path.join(
                tempfile.mkdtemp(prefix="p2p-chaos-"), "drill.wal")
        result["crash_replay"] = crash_replay_drill(
            pipe, trace, journal_path, crash_after, serve_kw=kw)
    return result


def crash_replay_drill(pipe, trace, journal_path, crash_after: int,
                       serve_kw=None) -> dict:
    """Simulated process death after ``crash_after`` terminal records,
    then a journaled restart over the same trace. Invariant: both runs
    together serve every request exactly once — nothing lost, nothing
    re-answered."""
    from p2p_tpu.serve import Journal, serve_forever
    from p2p_tpu.serve.engine_loop import TERMINAL_STATUSES

    kw = dict(serve_kw or {})
    if os.path.exists(journal_path):
        os.remove(journal_path)

    first: list = []
    journal = Journal(journal_path)
    gen = serve_forever(pipe, list(trace), journal=journal, **kw)
    for rec in gen:
        first.append(rec)
        if len(_terminal_records(first)) >= crash_after:
            break
    gen.close()
    # Simulated crash: the loop dies here. Close the raw handle (flush,
    # no final sync) — the WAL keeps whatever the crash left behind.
    journal._f.close()

    journal2 = Journal(journal_path)
    replay = journal2.replay_state
    second = list(serve_forever(pipe, list(trace), journal=journal2, **kw))
    journal2.close()

    # Strict exactly-once: a request that reached *any* terminal state
    # before the crash must not reach one again after the restart. The one
    # legitimate overlap is 'rejected' — duplicate-id admission rejections
    # are deliberately never journaled (a terminal WAL line for the
    # duplicate's id would make replay drop the still-live original).
    seen: dict = {}
    run2 = {r["request_id"]: r["status"] for r in _terminal_records(second)}
    for rec in _terminal_records(first):
        rid = rec["request_id"]
        if rid in run2 and "rejected" not in (rec["status"], run2[rid]):
            raise DrillFailure(
                f"crash-replay: request {rid!r} reached a terminal state in "
                f"both runs ({rec['status']!r}, then {run2[rid]!r})")
        seen.setdefault(rid, rec["status"])
    for rid, status in run2.items():
        seen.setdefault(rid, status)
    ids = [r["request_id"] for r in trace if "request_id" in r]
    missing = [rid for rid in ids if rid not in seen]
    if missing:
        raise DrillFailure(f"crash-replay: {len(missing)} request(s) lost "
                           f"across the crash: {missing[:5]}")
    summary2 = second[-1]
    return {
        "crash_after": crash_after,
        "replayed_pending": len(replay.pending),
        "already_terminal": len(replay.terminal),
        "skipped_corrupt": replay.skipped_corrupt,
        "replay": summary2.get("replay"),
        # Requests the crash caught *between* their phases resume in
        # phase 2 off the journaled hand-off spill (0 when the crash
        # landed elsewhere; the deterministic mid-hand-off case is pinned
        # by tests/test_handoff.py).
        "resumed_handoffs": summary2.get("phases", {}).get(
            "resumed_handoffs", 0),
    }


class _ShadowJournal:
    """A Journal that tees every appended WAL line into a side-car shadow
    file compaction never touches — the drill's full-history oracle: after
    any number of snapshot/rotate cycles, ``replay(shadow)`` is what a
    never-compacted journal would fold, so snapshot+tail correctness is
    *asserted* against it, not assumed."""

    def __init__(self, path, shadow_path):
        from p2p_tpu.serve import Journal

        self._shadow = open(shadow_path, "a", encoding="utf-8")
        self.journal = Journal(path)
        real_append = self.journal._append

        def tee(rec):
            real_append(rec)
            self._shadow.write(json.dumps(rec) + "\n")
            self._shadow.flush()

        self.journal._append = tee

    def close(self):
        self.journal.close()
        self._shadow.close()


def rolling_restart_drill(pipe, trace, journal_path, *, cycles=3,
                          kill_mid_drain=False, serve_kw=None) -> dict:
    """The lifecycle leg (ISSUE 9): N graceful drain/restart cycles
    mid-trace must be invisible in the results.

    Each cycle opens the same journal (warm restart: snapshot + WAL tail),
    re-feeds the full trace (already-terminal ids dedupe; drained-pending
    ones resume), requests a drain after its share of new terminal
    records, and exits through the drain protocol (snapshot + compaction).
    ``kill_mid_drain=True`` additionally arms a chaos ``kill_during_drain``
    in the middle cycle — that drain dies half-way (no compaction, no
    summary) and the next cycle must still restart exactly-once.

    Invariants raised as :class:`DrillFailure`:

    1. exactly-once: every request id reaches exactly one non-``rejected``
       terminal across the union of cycles (draining rejections are
       backpressure, deliberately un-journaled, and may repeat);
    2. bitwise: every ``ok`` image equals the uninterrupted run's;
    3. snapshot+tail ≡ full history: at every restart the live journal's
       fold (pending ids+dicts, terminal map, live hand-offs) is
       byte-equivalent (JSON) to folding the never-compacted shadow WAL;
    4. compaction wins: every restart after a completed drain replays
       strictly fewer WAL records than the full history holds.
    """
    from p2p_tpu.serve import replay as replay_fn
    from p2p_tpu.serve import serve_forever
    from p2p_tpu.serve.chaos import FaultPlan, SimulatedKill
    from p2p_tpu.serve.engine_loop import TERMINAL_STATUSES
    from p2p_tpu.serve.lifecycle import DrainController

    kw = dict(max_batch=4, max_wait_ms=20.0, queue_cap=256,
              validate_outputs=True, phase2_max_batch=4)
    kw.update(serve_kw or {})
    if "prewarm" not in kw:
        kw["prewarm"] = _prewarm_reps(pipe, trace)

    for p in (journal_path, journal_path + ".shadow",
              journal_path + ".snapshot"):
        if os.path.exists(p):
            os.remove(p)

    clean = list(serve_forever(pipe, list(trace), **kw))
    clean_by_id = check_exactly_once(trace, clean, "uninterrupted run")

    n_requests = len(clean_by_id)
    # One share per cycle plus one spare: a drain completes its in-flight
    # work past the trigger, so later cycles must still have enough left
    # to drain again (deterministic either way under a fixed timer).
    quota = max(1, n_requests // (cycles + 1))
    shadow = journal_path + ".shadow"
    resolved: dict = {}
    drains = completed_drains = kills = 0
    restart_tails = []
    full_history_records = 0

    def _shadow_records():
        with open(shadow) as f:
            return sum(1 for l in f if l.strip())

    def _fold_key(state):
        """The comparable fold: pending (ids + dicts, in order), terminal
        map, and live hand-offs keyed to their spill (path + spec)."""
        live = set(state.pending_ids)
        return json.dumps({
            "pending": state.pending,
            "terminal": dict(sorted(state.terminal.items())),
            "handoffs": {rid: {"carry_path": rec["carry_path"],
                               "spec": rec["spec"]}
                         for rid, rec in sorted(state.handoffs.items())
                         if rid in live}}, sort_keys=True)

    for cycle in range(cycles):
        ctl = DrainController()
        sj = _ShadowJournal(journal_path, shadow)
        live_state = sj.journal.replay_state
        if cycle > 0:
            full = replay_fn(shadow, sweep=False)
            if _fold_key(live_state) != _fold_key(full):
                raise DrillFailure(
                    f"rolling-restart cycle {cycle}: snapshot+tail fold "
                    f"diverged from the full-history fold")
            restart_tails.append(live_state.wal_records)
            full_history_records = full.wal_records
            if completed_drains and not \
                    live_state.wal_records < full_history_records:
                raise DrillFailure(
                    f"rolling-restart cycle {cycle}: compaction won "
                    f"nothing — tail replayed {live_state.wal_records} "
                    f"records vs {full_history_records} full history")
        chaos = None
        if kill_mid_drain and cycle == cycles // 2:
            # Armed at the cycle's first dispatch; fires after the first
            # drain-mode dispatch — this drain dies half-way.
            chaos = FaultPlan(by_batch={1: "kill_during_drain"})
        last = cycle == cycles - 1
        count = 0
        killed = False
        gen = serve_forever(pipe, list(trace), journal=sj.journal,
                            lifecycle=ctl, chaos=chaos, **kw)
        recs = []
        try:
            for rec in gen:
                recs.append(rec)
                if rec.get("status") in TERMINAL_STATUSES and \
                        rec["status"] != "rejected":
                    count += 1
                    if not last and count >= quota and not ctl.requested:
                        ctl.request(f"rolling cycle {cycle}")
                        drains += 1
        except SimulatedKill:
            killed = True
            kills += 1
            sj.journal._f.close()   # simulated death: no clean close
            sj._shadow.close()
        if not killed:
            if ctl.requested and recs and "drain" not in recs[-1]:
                raise DrillFailure(f"rolling-restart cycle {cycle}: drain "
                                   f"requested but the summary shows none")
            if ctl.requested:
                completed_drains += 1
            sj.close()
        for rec in recs:
            status = rec.get("status")
            if status not in TERMINAL_STATUSES or status == "rejected":
                continue
            rid = rec["request_id"]
            if rid in resolved:
                raise DrillFailure(
                    f"rolling-restart: request {rid!r} resolved twice "
                    f"({resolved[rid]['status']} then {status})")
            resolved[rid] = rec

    ids = [r["request_id"] for r in trace if "request_id" in r]
    missing = [rid for rid in ids if rid not in resolved]
    if missing:
        raise DrillFailure(f"rolling-restart: {len(missing)} request(s) "
                           f"lost across the cycles: {missing[:5]}")
    bitwise = check_bitwise_vs_clean(clean_by_id, resolved)
    counts: dict = {}
    for rec in resolved.values():
        counts[rec["status"]] = counts.get(rec["status"], 0) + 1
    return {"cycles": cycles,
            "n_requests": n_requests,
            "drains": drains,
            "completed_drains": completed_drains,
            "kills": kills,
            "counts": counts,
            "bitwise_compared": bitwise,
            "restart_tail_records": restart_tails,
            "full_history_records": full_history_records}


class _VirtualTimer:
    """Injected wall clock for the deterministic SLO policy drill."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt_s):
        self.t += dt_s


def _p99(vals):
    """Nearest-rank p99 (0 when empty) — matches the engine's summary
    percentile arithmetic."""
    if not vals:
        return 0.0
    v = sorted(vals)
    idx = min(len(v) - 1, max(0, int(round(0.99 * (len(v) - 1)))))
    return v[idx]


def slo_overload_drill(pipe, *, n=192, seed=11, steps=4, overload=2.0,
                       service_ms=80.0, max_batch=4) -> dict:
    """The SLO policy drill (ISSUE 12): a seeded tenant/tier/gate-mixed
    loadgen trace offered at ``overload``× the engine's service capacity,
    served through the full scheduler (weighted-fair admission, tenant
    quotas, tier-pure batches, phase-boundary preemption, per-tier
    degradation) on a *deterministic virtual clock* — every dispatched
    batch costs exactly ``service_ms`` of injected wall time, so the
    whole overload scenario replays byte-identically and the policy
    verdicts below are facts, not flakes.

    Invariants raised as :class:`DrillFailure`:

    1. **Shed order** — every ``shed`` record is a best-effort request:
       the degradation ladder never sheds a paid tier while best-effort
       traffic exists to absorb it.
    2. **Premium p99 bound** — premium p99 under the 2× overload stays
       within 1.2× of the *uncontended* premium p99 (the same premium
       requests at the same arrival stamps with no competing traffic).
    3. **Exactly-once** — every admitted request resolves to exactly one
       terminal record, preemptions and sheds included.

    Returns the ``serve.slo`` bench sub-record (frozen keys pinned in
    tests/test_bench_rehearsal.py)."""
    import importlib.util

    from p2p_tpu.serve import DegradeConfig, SloConfig, serve_forever

    spec = importlib.util.spec_from_file_location(
        "p2p_loadgen", os.path.join(_REPO, "tools", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    # Offered load = overload × capacity: the engine serves max_batch
    # lanes per service_ms quantum, loadgen offers rate requests/s.
    rate = overload * max_batch * 1000.0 / service_ms
    trace = loadgen.generate_trace(
        n, mode="poisson", rate_per_s=rate, seed=seed, steps=steps,
        gate_mix=loadgen.parse_gate_mix("0.5:1,off:1"),
        tenant_mix=loadgen.parse_name_mix("acme:2,globex:1,initech:1"),
        tier_mix=loadgen.parse_name_mix("premium:1,best_effort:3"))
    tier_of = {r["request_id"]: r.get("tier", "standard") for r in trace}

    # Tuned so the drill actually exercises every mechanism: the quota
    # binds (three tenants × 10 < the 2× backlog), preemption parks
    # between-phases best-effort work, the ladder reaches the shed rung
    # within a couple of service quanta, and min_bucket=4 keeps the
    # level-2 shrink a no-op — a shrunken cap would force in-band
    # compiles below the prewarmed bucket, charging premium latency for
    # a *compile*, which is the one cost compile-ahead exists to avoid.
    slo = SloConfig(tenant_quota=10, preempt_depth=8)
    degrade = DegradeConfig(depth_threshold=8, window_ms=service_ms,
                            min_bucket=4)

    def run(reqs):
        from p2p_tpu.serve import Request

        timer = _VirtualTimer()

        class Runner:
            def __init__(self, compile_key, bucket):
                self.bucket = bucket

            def warm(self, entries):
                timer.advance(2 * service_ms / 1000.0)

            def __call__(self, entries, guidance):
                import numpy as np

                timer.advance(service_ms / 1000.0)
                g = len(entries[0].request.prompts)
                return np.zeros((self.bucket, g, 2, 2, 3), np.uint8)

        objs = [Request.from_dict(d) for d in reqs]
        return list(serve_forever(
            pipe, objs, runner_factory=Runner, timer=timer,
            max_batch=max_batch, phase2_max_batch=max_batch,
            max_wait_ms=service_ms, queue_cap=4 * n,
            prewarm=_prewarm_reps(pipe, reqs), slo=slo, degrade=degrade))

    recs = run(trace)
    check_exactly_once(trace, recs, "slo overload run")
    summary = recs[-1]

    def _lat(records, tier):
        return [r["total_ms"] for r in records
                if r.get("status") == "ok"
                and tier_of.get(r.get("request_id")) == tier]

    shed_tiers = [tier_of[r["request_id"]] for r in recs
                  if r.get("status") == "shed"]
    paid_shed = sum(1 for t in shed_tiers if t != "best_effort")
    if paid_shed:
        raise DrillFailure(
            f"slo overload: {paid_shed} paid-tier request(s) shed while "
            f"best-effort traffic existed — the ladder must shed "
            f"best-effort first (shed tiers: {sorted(set(shed_tiers))})")

    # Uncontended baseline: the SAME premium requests at the SAME arrival
    # stamps, with no competing traffic (arrival order is preserved, so
    # the trace stays sorted).
    premium = [r for r in trace if r.get("tier") == "premium"]
    unc = run(premium)
    check_exactly_once(premium, unc, "uncontended premium run")
    p99_over = _p99(_lat(recs, "premium"))
    p99_unc = _p99(_lat(unc, "premium"))
    ratio = p99_over / p99_unc if p99_unc > 0 else 0.0
    if p99_unc <= 0:
        raise DrillFailure("slo overload: uncontended premium p99 is 0 — "
                           "the baseline run served nothing measurable")
    if ratio > 1.2:
        raise DrillFailure(
            f"slo overload: premium p99 {p99_over:.1f}ms is {ratio:.2f}x "
            f"its uncontended p99 {p99_unc:.1f}ms (> 1.2x) — the "
            f"scheduler failed to protect the paid tier")
    slo_block = summary.get("slo", {})
    return {
        "n_requests": n,
        "overload_factor": overload,
        "premium_p99_ms": round(p99_over, 2),
        "premium_uncontended_p99_ms": round(p99_unc, 2),
        "premium_p99_ratio": round(ratio, 4),
        "best_effort_shed": len(shed_tiers) - paid_shed,
        "paid_shed": paid_shed,
        "preemptions": slo_block.get("preemptions", 0),
        "preempt_resumes": slo_block.get("preempt_resumes", 0),
        "quota_rejects": slo_block.get("quota_rejects", 0),
    }


def preempt_kill_drill(pipe, journal_path, *, steps=3,
                       serve_kw=None) -> dict:
    """The preemption durability drill (ISSUE 12): a chaos
    ``preempt_then_kill`` forces a gated request's preemption at its
    phase boundary (carry spilled, ``preempted`` WAL record), then the
    process dies before the parked work resumes. The restart must fold
    the preempted record exactly like a crashed hand-off: the victim
    resumes in phase 2 off the spill, every request reaches exactly one
    terminal across the union of both runs, and every ``ok`` output is
    bitwise-identical to the never-preempted run."""
    from p2p_tpu.serve import (FaultPlan, Journal, Request, SimulatedKill,
                               serve_forever)
    from p2p_tpu.serve.chaos import PREEMPT_THEN_KILL

    prompts = ("a cat riding a bike", "a dog riding a bike")

    def req(rid, arrival, gate=None, seed=0):
        return {"request_id": rid, "prompt": prompts[0],
                "target": prompts[1], "mode": "replace", "steps": steps,
                "seed": seed, "arrival_ms": arrival,
                **({"gate": gate} if gate is not None else {})}

    victim = "pk-victim"
    trace = [req(victim, 0.0, gate=0.5, seed=42),
             req("pk-g1", 1.0, gate=0.5, seed=43),
             req("pk-u0", 2.0, seed=7),
             req("pk-g2", 500.0, gate=0.5, seed=44)]
    kw = dict(max_batch=4, max_wait_ms=20.0, queue_cap=64,
              phase2_max_batch=4)
    kw.update(serve_kw or {})
    if "prewarm" not in kw:
        kw["prewarm"] = _prewarm_reps(pipe, trace)

    def to_reqs():
        return [Request.from_dict(d) for d in trace]

    clean = list(serve_forever(pipe, to_reqs(), **kw))
    clean_by_id = check_exactly_once(trace, clean, "never-preempted run")

    if os.path.exists(journal_path):
        os.remove(journal_path)
    plan = FaultPlan(by_request={victim: PREEMPT_THEN_KILL})
    journal = Journal(journal_path)
    first: list = []
    killed = False
    gen = serve_forever(pipe, to_reqs(), journal=journal, chaos=plan, **kw)
    try:
        for rec in first_iter(gen, first):
            pass
    except SimulatedKill:
        killed = True
        journal._f.close()   # simulated death: no clean close
    if not killed:
        raise DrillFailure("preempt_then_kill never fired — the victim's "
                           "phase boundary was never reached")

    journal2 = Journal(journal_path)
    if victim not in journal2.replay_state.handoffs:
        raise DrillFailure("the preempted record did not fold into the "
                           "replay hand-off map — the victim would re-run "
                           "phase 1 instead of resuming off its spill")
    second = list(serve_forever(pipe, to_reqs(), journal=journal2, **kw))
    journal2.close()

    seen: dict = {}
    run2 = {r["request_id"]: r for r in _terminal_records(second)}
    for rec in _terminal_records(first):
        rid = rec["request_id"]
        if rid in run2 and "rejected" not in (rec["status"],
                                              run2[rid]["status"]):
            raise DrillFailure(
                f"preempt_then_kill: request {rid!r} reached a terminal "
                f"state in both runs ({rec['status']!r}, then "
                f"{run2[rid]['status']!r})")
        seen.setdefault(rid, rec)
    for rid, rec in run2.items():
        seen.setdefault(rid, rec)
    ids = [r["request_id"] for r in trace]
    missing = [rid for rid in ids if rid not in seen]
    if missing:
        raise DrillFailure(f"preempt_then_kill: {len(missing)} request(s) "
                           f"lost across the kill: {missing}")
    bitwise = check_bitwise_vs_clean(clean_by_id, seen)
    summary2 = second[-1]
    resumed = summary2.get("phases", {}).get("resumed_handoffs", 0)
    if resumed < 1:
        raise DrillFailure("the restart served the victim without "
                           "resuming off the preemption spill")
    return {
        "n_requests": len(ids),
        "killed": killed,
        "bitwise_compared": bitwise,
        "resumed_handoffs": resumed,
        "replay_skipped_corrupt": journal2.replay_state.skipped_corrupt,
    }


def cache_parity_drill(pipe, *, n=32, seed=13, steps=3, zipf_s=1.1,
                       zipf_universe=16, gate=0.5, rate_per_s=10.0,
                       l3_bytes=None, serve_kw=None) -> dict:
    """The semantic-cache parity drill (ISSUE 13): a seeded ``--zipf``
    repeat-heavy trace served twice — uncached, then through a fresh
    :class:`~p2p_tpu.serve.SemCache` — must produce **bitwise-identical
    ok outputs** with a real fraction of the traffic served from cache.
    The gate's default-on ``cache_parity`` leg and the bench
    ``serve.cache`` sub-record both read the returned facts.

    Every request is gated (``gate=0.5``) so all three layers are live;
    ``rate_per_s`` spaces virtual arrivals so repeats land both while
    their leader is still in flight (single-flight collapse) and after
    it completed (real L3/L2 lookups) — at a dense rate everything
    collapses and the stores are never read; ``l3_bytes`` defaults to
    two entries' worth of images, so the L3
    budget actually evicts under the zipf universe and repeats of evicted
    content fall through to the L2 prefix store — the drill exercises
    hit, miss, eviction AND the L2 fallback on one deterministic trace.
    The headline number is ``amplification``: images/sec cached over
    images/sec uncached at the identical offered trace (equal
    device-seconds of demand) — the traffic the cache serves without
    computing it."""
    import importlib.util

    import numpy as np

    from p2p_tpu.serve import Request, SemCache, serve_forever

    spec = importlib.util.spec_from_file_location(
        "p2p_loadgen", os.path.join(_REPO, "tools", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    trace = loadgen.generate_trace(
        n, mode="poisson", rate_per_s=rate_per_s, seed=seed, steps=steps,
        gate=gate, zipf_s=zipf_s, zipf_universe=zipf_universe)
    kw = dict(max_batch=4, max_wait_ms=20.0, queue_cap=256,
              phase2_max_batch=4)
    kw.update(serve_kw or {})
    if "prewarm" not in kw:
        kw["prewarm"] = _prewarm_reps(pipe, trace)

    def run(semcache):
        return list(serve_forever(pipe,
                                  [Request.from_dict(d) for d in trace],
                                  semcache=semcache, **kw))

    run(None)                                   # warm programs unmeasured
    clean = run(None)
    clean_by_id = check_exactly_once(trace, clean, "uncached run")
    if l3_bytes is None:
        # Two entries' worth: the zipf universe then forces L3 evictions
        # and L2 fallbacks on the same trace.
        sample = next(r["images"] for r in clean if r["status"] == "ok")
        l3_bytes = 2 * int(np.asarray(sample).nbytes)
    sc = SemCache(spill_dir=os.path.join(
        tempfile.mkdtemp(prefix="p2p-semcache-"), "spill"),
        l3_bytes=l3_bytes)
    cached = run(sc)
    cached_by_id = check_exactly_once(trace, cached, "cached run")
    bitwise = check_bitwise_vs_clean(clean_by_id, cached_by_id)
    if bitwise != sum(1 for r in clean_by_id.values()
                      if r["status"] == "ok"):
        raise DrillFailure(
            f"cache parity: cached run served {bitwise} ok vs the "
            f"uncached run's — a cached serve dropped or degraded traffic")

    block = cached[-1]["semcache"]
    served = block["served_from_cache"]
    stats = block["layers"]

    def hit_rate(layer):
        s = stats[layer]
        return round(s["hits"] / max(s["hits"] + s["misses"], 1), 4)

    amp = clean[-1]["makespan_ms"] / max(cached[-1]["makespan_ms"], 1e-9)
    return {
        "n_requests": n,
        "zipf_s": zipf_s,
        "served_from_cache": served,
        "served_from_cache_fraction": round(served / n, 4),
        "l1_hits": stats["l1"]["hits"],
        "l2_hits": stats["l2"]["hits"],
        "l3_hits": stats["l3"]["hits"],
        "l1_hit_rate": hit_rate("l1"),
        "l2_hit_rate": hit_rate("l2"),
        "l3_hit_rate": hit_rate("l3"),
        "l3_evictions": stats["l3"]["evictions"],
        "collapsed": block["served"]["collapsed"],
        "uncached_makespan_ms": round(clean[-1]["makespan_ms"], 1),
        "cached_makespan_ms": round(cached[-1]["makespan_ms"], 1),
        "amplification": round(amp, 3),
    }


def cache_insert_kill_drill(pipe, journal_path, *, steps=3) -> dict:
    """The cache durability drill (ISSUE 13): a chaos
    ``kill_after_cache_insert`` dies between the leader's L3 insert (spill
    + journaled ``cache`` record, both durable) and its terminal fsync.
    The restart must reseed the cache off the journal and serve the
    still-pending leader AND its followers from the durable insert —
    exactly-once across the union of both runs, outputs bitwise-identical
    to the uncached run, zero corrupt records."""
    import numpy as np

    from p2p_tpu.serve import (FaultPlan, Journal, Request, SemCache,
                               SimulatedKill, serve_forever)
    from p2p_tpu.serve.chaos import KILL_AFTER_CACHE_INSERT

    prompts = ("a cat riding a bike", "a dog riding a bike")

    def req(rid, arrival, seed=42):
        return {"request_id": rid, "prompt": prompts[0],
                "target": prompts[1], "mode": "replace", "steps": steps,
                "seed": seed, "gate": 0.5, "arrival_ms": arrival}

    leader = "ck-leader"
    trace = [req(leader, 0.0), req("ck-f1", 1.0), req("ck-f2", 2.0),
             req("ck-distinct", 3.0, seed=9)]
    kw = dict(max_batch=4, max_wait_ms=20.0, queue_cap=64,
              phase2_max_batch=4, prewarm=_prewarm_reps(pipe, trace))

    def to_reqs():
        return [Request.from_dict(d) for d in trace]

    clean = list(serve_forever(pipe, to_reqs(), **kw))
    clean_by_id = check_exactly_once(trace, clean, "uncached run")

    workdir = os.path.dirname(journal_path)
    if os.path.exists(journal_path):
        os.remove(journal_path)
    plan = FaultPlan(by_request={leader: KILL_AFTER_CACHE_INSERT})
    journal = Journal(journal_path)
    sc = SemCache(spill_dir=os.path.join(workdir, "semcache"))
    first: list = []
    killed = False
    gen = serve_forever(pipe, to_reqs(), journal=journal, chaos=plan,
                        semcache=sc, **kw)
    try:
        for rec in first_iter(gen, first):
            pass
    except SimulatedKill:
        killed = True
        journal._f.close()   # simulated death: no clean close
    if not killed:
        raise DrillFailure("kill_after_cache_insert never fired — the "
                           "leader's L3 insert was never reached")

    journal2 = Journal(journal_path)
    if not journal2.replay_state.cache_entries:
        raise DrillFailure("the journaled cache record did not fold into "
                           "replay — the restart would recompute what the "
                           "durable insert already holds")
    sc2 = SemCache(spill_dir=os.path.join(workdir, "semcache"))
    second = list(serve_forever(pipe, to_reqs(), journal=journal2,
                                semcache=sc2, **kw))
    journal2.close()

    seen: dict = {}
    run2 = {r["request_id"]: r for r in _terminal_records(second)}
    for rec in _terminal_records(first):
        rid = rec["request_id"]
        if rid in run2 and "rejected" not in (rec["status"],
                                              run2[rid]["status"]):
            raise DrillFailure(
                f"kill_after_cache_insert: request {rid!r} reached a "
                f"terminal state in both runs ({rec['status']!r}, then "
                f"{run2[rid]['status']!r})")
        seen.setdefault(rid, rec)
    for rid, rec in run2.items():
        seen.setdefault(rid, rec)
    ids = [r["request_id"] for r in trace]
    missing = [rid for rid in ids if rid not in seen]
    if missing:
        raise DrillFailure(f"kill_after_cache_insert: {len(missing)} "
                           f"request(s) lost across the kill: {missing}")
    bitwise = check_bitwise_vs_clean(clean_by_id, seen)
    summary2 = second[-1]
    served = summary2.get("semcache", {}).get("served_from_cache", 0)
    if served < 1:
        raise DrillFailure("the restart recomputed everything — the "
                           "durable cache insert served nothing")
    followers_ok = sum(
        1 for rid in ("ck-f1", "ck-f2")
        if seen.get(rid, {}).get("status") == "ok"
        and np.array_equal(np.asarray(seen[rid]["images"]),
                           np.asarray(clean_by_id[rid]["images"])))
    return {
        "n_requests": len(ids),
        "killed": killed,
        "bitwise_compared": bitwise,
        "followers_bitwise": followers_ok,
        "restart_served_from_cache": served,
        "replay_skipped_corrupt": journal2.replay_state.skipped_corrupt,
    }


def _elastic_real_factory(pipe, timer, service_ms):
    """Mesh-aware, virtual-clock real-runner factory for the elastic
    drills: builds the engine's *real* runner for whatever topology the
    (mesh-tagged) compile key names, charging ``service_ms`` of injected
    virtual time per dispatch — so the diurnal pressure swings are
    deterministic AND the outputs are real pipeline numerics the parity
    check can bite on. One default factory (weight replication included)
    is built lazily per distinct dp and shared by every runner at that
    width."""
    from p2p_tpu.serve.meshing import MESH_KEY_TAG, MeshSpec, build_mesh
    from p2p_tpu.serve.programs import default_runner_factory

    inner_by_dp: dict = {}

    def inner_factory(dp):
        if dp not in inner_by_dp:
            mesh = build_mesh(MeshSpec(dp=dp)) if dp else None
            inner_by_dp[dp] = default_runner_factory(pipe, mesh=mesh)
        return inner_by_dp[dp]

    def make(compile_key, bucket):
        dp = 0  # untagged key = the mesh-less engine (the fixed baseline)
        if (compile_key and isinstance(compile_key[-1], tuple)
                and len(compile_key[-1]) == 3
                and compile_key[-1][0] == MESH_KEY_TAG):
            dp = int(compile_key[-1][2])
        inner = inner_factory(dp)(compile_key, bucket)

        class Wrapped:
            def __init__(self):
                self.bucket = bucket

            def warm(self, entries):
                # Warm time is charged to the virtual clock too, so the
                # engine's prewarm_ms bookkeeping measures something
                # deterministic (the real compile happens out-of-band of
                # the virtual service timeline either way).
                timer.advance(2 * service_ms / 1000.0)
                return inner.warm(entries)

            def __call__(self, entries, guidance):
                timer.advance(service_ms / 1000.0)
                return inner(entries, guidance)

        return Wrapped()

    return make


def elastic_resize_drill(pipe, journal_path=None, *, n=192, seed=19,
                         steps=3, service_ms=60.0, max_batch=2) -> dict:
    """The elastic serving drill (ISSUE 19), three legs:

    1. **Diurnal autonomy** — a seeded loadgen ``--diurnal`` trace (peaks
       well above dp=1 capacity, troughs well below) served with
       ``elastic`` on, real runners on a deterministic virtual clock: the
       engine must resize dp up AND down at least twice each, drop
       nothing (zero rejected/shed), and resolve every request
       exactly-once.
    2. **Fixed-topology parity** — the same trace through the mesh-less
       fixed engine: every ``ok`` output must match within the repo's
       documented vmap tolerance (±1 uint8 step, serve/meshing.py) — a
       resize may change *where* a lane runs, never what it computes
       beyond that bound.
    3. **Mid-resize crash** — a gated burst with chaos
       ``kill_during_resize``: the process dies after the ``resize``
       record is durable but before cutover. The restart must come back
       on the WAL-recorded *target* topology, resume every parked carry
       off its spill, and the union of both runs must be exactly-once
       with ok-outputs bitwise-identical to the uninterrupted elastic
       run.

    Returns the ``serve.elastic`` bench sub-record (frozen keys pinned in
    tests/test_bench_rehearsal.py)."""
    import importlib.util

    import jax
    import numpy as np

    from p2p_tpu.serve import (ElasticConfig, FaultPlan, Journal, Request,
                               SimulatedKill, serve_forever)
    from p2p_tpu.serve.chaos import KILL_DURING_RESIZE

    if len(jax.devices()) < 4:
        raise DrillFailure(
            f"elastic_resize_drill needs >= 4 devices for a 1<->2<->4 dp "
            f"swing; this process has {len(jax.devices())} (virtual CPU "
            f"meshes: --xla_force_host_platform_device_count)")

    spec = importlib.util.spec_from_file_location(
        "p2p_loadgen", os.path.join(_REPO, "tools", "loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)

    # Offered load swings around dp=1 capacity (max_batch lanes per
    # service_ms): peaks at 3.5x justify growing toward dp=4, troughs at
    # 0.05x let the widened mesh drain and go calm so it shrinks back —
    # several full day-cycles per trace, so the >=2-each resize floor is
    # structural, not lucky. The time-averaged offered rate sits between
    # dp=1 and dp=2 capacity: a frozen dp=1 engine lags the whole trace,
    # the elastic one keeps catching up (which is the point).
    capacity = max_batch * 1000.0 / service_ms
    trace = loadgen.generate_trace(
        n, mode="poisson", rate_per_s=capacity, seed=seed,
        steps=steps, diurnal={"period_ms": 1200.0, "low": 0.05,
                              "high": 3.5})
    cfg = ElasticConfig(up_depth=3, up_window_ms=40.0, down_depth=2,
                        down_window_ms=150.0, cooldown_ms=100.0, max_dp=4)
    kw = dict(max_batch=max_batch, max_wait_ms=20.0, queue_cap=4 * n,
              phase2_max_batch=max_batch)

    def to_reqs(t):
        return [Request.from_dict(d) for d in t]

    def run(elastic):
        timer = _VirtualTimer()
        return list(serve_forever(
            pipe, to_reqs(trace), timer=timer,
            runner_factory=_elastic_real_factory(pipe, timer, service_ms),
            prewarm=_prewarm_reps(pipe, trace), elastic=elastic, **kw))

    recs = run(cfg)
    by_id = check_exactly_once(trace, recs, "elastic diurnal run")
    dropped = sum(1 for r in _terminal_records(recs)
                  if r["status"] in ("rejected", "shed"))
    if dropped:
        raise DrillFailure(f"elastic diurnal run dropped {dropped} "
                           f"request(s) — resizing must add capacity, "
                           f"never shed work")
    summary = recs[-1]
    stats = summary.get("elastic", {})
    if stats.get("resizes_up", 0) < 2 or stats.get("resizes_down", 0) < 2:
        raise DrillFailure(
            f"elastic diurnal run resized up {stats.get('resizes_up')}x / "
            f"down {stats.get('resizes_down')}x — the drill needs >= 2 "
            f"each (timeline: {stats.get('timeline')})")
    if stats.get("prewarm_ms", 0) <= 0:
        raise DrillFailure("resizes committed with zero prewarm time — "
                           "cutovers must compile-ahead, never in-band")

    # Leg 2: fixed-topology parity at the documented vmap tolerance.
    fixed = run(None)
    fixed_by_id = check_exactly_once(trace, fixed, "fixed-topology run")
    max_abs = 0
    compared = 0
    for rid, rec in by_id.items():
        if rec["status"] != "ok":
            continue
        ref = fixed_by_id.get(rid)
        if ref is None or ref["status"] != "ok":
            raise DrillFailure(f"request {rid!r} is ok under elastic but "
                               f"not in the fixed-topology run")
        delta = int(np.max(np.abs(
            np.asarray(rec["images"], np.int16)
            - np.asarray(ref["images"], np.int16)))) if np.asarray(
                rec["images"]).size else 0
        max_abs = max(max_abs, delta)
        compared += 1
    if compared == 0:
        raise DrillFailure("elastic parity compared zero ok outputs")
    if max_abs > 1:
        raise DrillFailure(
            f"elastic vs fixed-topology outputs differ by {max_abs} uint8 "
            f"steps (documented vmap tolerance: 1) — a resize changed "
            f"the numerics")

    # Leg 3: kill_during_resize — die between the durable resize record
    # and cutover; restart on the WAL target topology, exactly-once.
    kill = {}
    if journal_path is not None:
        prompts = ("a cat riding a bike", "a dog riding a bike")
        ktrace = [{"request_id": f"ez-{i}", "prompt": prompts[0],
                   "target": prompts[1], "mode": "replace", "steps": steps,
                   "seed": 40 + i, "gate": 0.5, "arrival_ms": float(i)}
                  for i in range(6)]
        # max_dp=2 + a long cooldown pin the whole post-resize tail to
        # dp=2 in BOTH the uninterrupted and the crashed+restarted run,
        # so the union comparison can demand bitwise equality.
        kcfg = ElasticConfig(up_depth=2, up_window_ms=0.0, down_depth=1,
                             down_window_ms=1e6, cooldown_ms=1e6, max_dp=2)

        def krun(elastic, journal=None, chaos=None, sink=None):
            timer = _VirtualTimer()
            gen = serve_forever(
                pipe, to_reqs(ktrace), timer=timer,
                runner_factory=_elastic_real_factory(pipe, timer,
                                                     service_ms),
                prewarm=_prewarm_reps(pipe, ktrace), elastic=elastic,
                journal=journal, chaos=chaos, **kw)
            if sink is None:
                return list(gen)
            for _ in first_iter(gen, sink):
                pass
            return sink

        kclean = krun(kcfg)
        kclean_by_id = check_exactly_once(ktrace, kclean,
                                          "uninterrupted elastic run")
        if os.path.exists(journal_path):
            os.remove(journal_path)
        plan = FaultPlan(by_request={"ez-0": KILL_DURING_RESIZE})
        journal = Journal(journal_path)
        first: list = []
        killed = False
        try:
            krun(kcfg, journal=journal, chaos=plan, sink=first)
        except SimulatedKill:
            killed = True
            journal._f.close()   # simulated death: no clean close
        if not killed:
            raise DrillFailure("kill_during_resize never fired — no "
                               "resize ran after the kill was armed")

        journal2 = Journal(journal_path)
        if journal2.replay_state.mesh_dp != 2:
            raise DrillFailure(
                f"the WAL's resize record did not fold: replay mesh_dp = "
                f"{journal2.replay_state.mesh_dp}, expected the target "
                f"topology 2")
        second = krun(kcfg, journal=journal2)
        journal2.close()
        restart_timeline = second[-1].get("mesh", {}).get("timeline", [])
        if not restart_timeline or restart_timeline[0]["dp"] != 2:
            raise DrillFailure(
                f"the restart did not resume on the WAL target topology "
                f"(timeline: {restart_timeline})")

        seen: dict = {}
        run2 = {r["request_id"]: r for r in _terminal_records(second)}
        for rec in _terminal_records(first):
            rid = rec["request_id"]
            if rid in run2 and "rejected" not in (rec["status"],
                                                  run2[rid]["status"]):
                raise DrillFailure(
                    f"kill_during_resize: request {rid!r} reached a "
                    f"terminal state in both runs ({rec['status']!r}, "
                    f"then {run2[rid]['status']!r})")
            seen.setdefault(rid, rec)
        for rid, rec in run2.items():
            seen.setdefault(rid, rec)
        missing = [r["request_id"] for r in ktrace
                   if r["request_id"] not in seen]
        if missing:
            raise DrillFailure(f"kill_during_resize: {len(missing)} "
                               f"request(s) lost across the kill: "
                               f"{missing}")
        kbitwise = check_bitwise_vs_clean(kclean_by_id, seen)
        resumed = second[-1].get("phases", {}).get("resumed_handoffs", 0)
        if resumed < 1:
            raise DrillFailure("the restart served the parked carries "
                               "without resuming off their spills")
        kill = {
            "killed": killed,
            "restart_dp": restart_timeline[0]["dp"],
            "bitwise_compared": kbitwise,
            "resumed_handoffs": resumed,
            "replay_skipped_corrupt":
                journal2.replay_state.skipped_corrupt,
        }

    return {
        "n_requests": n,
        "resizes_up": stats["resizes_up"],
        "resizes_down": stats["resizes_down"],
        "prewarm_ms": stats["prewarm_ms"],
        "cutover_pause_p95_ms": stats["cutover_pause_p95_ms"],
        "parked": stats["parked"],
        "resumed": stats["resumed"],
        "dropped": dropped,
        "parity_compared": compared,
        "parity_max_abs": max_abs,
        **({"kill": kill} if kill else {}),
    }


def first_iter(gen, sink):
    """Iterate ``gen`` appending into ``sink`` — keeps the try/except at
    the call site tight while the kill can fire mid-iteration."""
    for rec in gen:
        sink.append(rec)
        yield rec


def main(argv=None) -> int:
    _pin_cpu()
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=24)
    ap.add_argument("--seed", type=int, default=8)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--fault-rate", type=float, default=0.25)
    ap.add_argument("--cancel-rate", type=float, default=0.1)
    ap.add_argument("--fault-kinds", default="transient,poison,nan",
                    help="comma list from the chaos catalog "
                         "(p2p_tpu.serve.chaos.KINDS); add 'hang' with "
                         "--watchdog-ms and 'fatal' to drill the drain "
                         "path")
    ap.add_argument("--trace", default=None,
                    help="drill an existing loadgen JSONL trace instead of "
                         "generating one")
    ap.add_argument("--plan", default=None,
                    help="fault-plan JSON for --trace (loadgen "
                         "--fault-rate writes it)")
    ap.add_argument("--watchdog-ms", type=float, default=None)
    ap.add_argument("--crash-after", type=int, default=None, metavar="K",
                    help="also run the crash-replay drill: abandon the "
                         "journaled run after K terminal records, restart, "
                         "assert exactly-once across both")
    ap.add_argument("--journal", default=None,
                    help="WAL path for --crash-after/--rolling "
                         "(default: a tempdir)")
    ap.add_argument("--rolling", type=int, default=None, metavar="N",
                    help="also run the rolling-restart lifecycle leg: N "
                         "graceful drain/restart cycles mid-trace (journal "
                         "snapshot+compaction at each drain) must yield "
                         "exactly-once terminals, ok-outputs bitwise-"
                         "identical to the uninterrupted run, and "
                         "snapshot+tail restarts that replay strictly "
                         "fewer WAL records than the full history")
    ap.add_argument("--kill-mid-drain", action="store_true",
                    help="with --rolling: arm a chaos kill_during_drain in "
                         "the middle cycle (that drain dies half-way; the "
                         "restart must still be exactly-once)")
    ap.add_argument("--slo-overload", action="store_true",
                    help="also run the SLO policy drill (ISSUE 12): a "
                         "tenant/tier-mixed trace at 2x overload on a "
                         "deterministic virtual clock must shed best-"
                         "effort only and hold premium p99 within 1.2x "
                         "of its uncontended p99")
    ap.add_argument("--preempt-kill", action="store_true",
                    help="also run the preemption durability drill "
                         "(ISSUE 12): chaos preempt_then_kill parks a "
                         "gated request's carry then dies; the restart "
                         "must resume it off the spill exactly-once with "
                         "bitwise-identical output")
    ap.add_argument("--cache-parity", action="store_true",
                    help="also run the semantic-cache parity drill "
                         "(ISSUE 13): a seeded --zipf repeat-heavy trace "
                         "served cached vs uncached must be bitwise-"
                         "identical with a real served-from-cache "
                         "fraction (L3 evictions + L2 fallback included)")
    ap.add_argument("--cache-kill", action="store_true",
                    help="also run the cache durability drill (ISSUE 13): "
                         "chaos kill_after_cache_insert dies between the "
                         "leader's L3 insert and its terminal fsync; the "
                         "restart must serve leader+followers off the "
                         "journaled insert exactly-once, bitwise")
    ap.add_argument("--elastic", action="store_true",
                    help="also run the elastic resize drill (ISSUE 19): "
                         "a seeded diurnal trace must resize dp up and "
                         "down >= 2x each with zero drops, match the "
                         "fixed-topology run within the documented vmap "
                         "tolerance, and survive a chaos "
                         "kill_during_resize with the restart resuming "
                         "on the WAL-recorded target topology")
    ap.add_argument("--warmup", action="store_true",
                    help="one unmeasured clean pass first, so the p95 "
                         "delta is retry cost, not compile noise")
    args = ap.parse_args(argv)

    if (args.trace is None) != (args.plan is None):
        ap.error("--trace and --plan go together")
    if args.trace:
        from p2p_tpu.serve.chaos import FaultPlan

        with open(args.trace) as f:
            trace = [json.loads(l) for l in f if l.strip()]
        plan = FaultPlan.load(args.plan)
    else:
        from p2p_tpu.serve import chaos

        kinds = tuple(k for k in args.fault_kinds.split(",") if k)
        unknown = [k for k in kinds if k not in chaos.KINDS]
        if unknown:
            # The catalog is the single vocabulary (ISSUE 20 satellite):
            # a typo'd kind would silently plan zero faults of that kind.
            ap.error(f"--fault-kinds {unknown} not in the chaos catalog "
                     f"(known: {', '.join(chaos.KINDS)})")
        trace, plan = standard_trace(args.n, args.seed, args.steps,
                                     args.fault_rate, args.cancel_rate,
                                     kinds)

    print(f"chaos drill: {sum('request_id' in r for r in trace)} requests, "
          f"{len(plan)} planned faults "
          f"({json.dumps(plan.to_dict()['by_request'], sort_keys=True)})",
          file=sys.stderr)
    pipe = tiny_pipeline()
    try:
        result = run_drill(pipe, trace, plan, watchdog_ms=args.watchdog_ms,
                           journal_path=args.journal,
                           crash_after=args.crash_after, warmup=args.warmup)
        if args.rolling:
            jpath = args.journal or os.path.join(
                tempfile.mkdtemp(prefix="p2p-rolling-"), "rolling.wal")
            result["rolling_restart"] = rolling_restart_drill(
                pipe, [r for r in trace if "cancel" not in r], jpath,
                cycles=args.rolling, kill_mid_drain=args.kill_mid_drain)
        if args.slo_overload:
            result["slo"] = slo_overload_drill(pipe)
        if args.preempt_kill:
            jpath = args.journal or os.path.join(
                tempfile.mkdtemp(prefix="p2p-preempt-"), "preempt.wal")
            result["preempt_kill"] = preempt_kill_drill(pipe, jpath)
        if args.cache_parity:
            result["cache"] = cache_parity_drill(pipe)
        if args.cache_kill:
            jpath = args.journal or os.path.join(
                tempfile.mkdtemp(prefix="p2p-cachekill-"), "cache.wal")
            result["cache_kill"] = cache_insert_kill_drill(pipe, jpath)
        if args.elastic:
            jpath = args.journal or os.path.join(
                tempfile.mkdtemp(prefix="p2p-elastic-"), "elastic.wal")
            result["elastic"] = elastic_resize_drill(pipe, jpath)
    except DrillFailure as e:
        print(f"DRILL FAILED: {e}", file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2, sort_keys=True))
    print("drill OK: every request reached exactly one terminal state; "
          f"{result['bitwise_compared']} ok outputs bitwise-identical to "
          "the fault-free run", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
