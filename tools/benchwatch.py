"""Regression watch over the committed BENCH_r*.json trajectory.

Five rounds of bench history live at the repo root (``BENCH_r01.json`` …),
each holding the round's parsed headline JSON line. The trajectory is the
product — 0.24 → 0.97 img/s/chip — and nothing guarded it: a PR could
halve the serve p95 budget or double the telemetry overhead and the next
round's json would just quietly record it. This tool is the watchdog:
compare the latest round against its predecessor on the headline keys and
exit nonzero past a configurable regression threshold.

    python tools/benchwatch.py                    # latest vs predecessor
    python tools/benchwatch.py --threshold 0.05   # tighter budget
    python tools/benchwatch.py --root DIR         # a different archive

Comparability rules (the committed history mixes tiny-CPU fallback rounds
with on-chip rounds):

- The predecessor is the most recent earlier round whose headline
  ``metric`` matches the latest round's — an on-chip sd14 round is never
  diffed against a tiny-CPU fallback (a 94% "regression" that is really a
  preset change). No comparable predecessor — an empty archive, a
  single-round trajectory, or a metric with no earlier twin — is an
  explicit "no comparable round" note and exit 0, never a silently-green
  table of per-key ``n/a`` rows.
- A key is compared only when both rounds carry it numerically; missing
  keys report ``n/a`` and never fail the watch (early rounds predate the
  serve/obs blocks).

Wired into ``tools/quality_gate.py`` as the opt-in ``bench_trend`` check
(``--bench-trend`` or ``--only bench_trend``); rehearsal-scale coverage in
``tests/test_benchwatch.py``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional, Tuple

#: (dotted key in the parsed round json, unit label, direction). Direction
#: says which way is better: a "higher" key regresses when it drops by
#: more than the threshold, a "lower" key when it grows by more.
HEADLINE_KEYS: Tuple[Tuple[str, str, str], ...] = (
    ("value", "img/s/chip", "higher"),
    ("phase1_ms_per_step", "ms/step", "lower"),
    ("phase2_ms_per_step", "ms/step", "lower"),
    # ISSUE 15: the searched per-site reuse schedule's speedup over the
    # ungated baseline at the same operating point (the generalized-gate
    # headline; ≥1.5× is the ISSUE target, vs 1.41× for the single
    # gate). Missing in pre-schedule rounds → n/a per the contract.
    ("gate.schedule.speedup", "x", "higher"),
    # ISSUE 16: the fused in-kernel-edit attention's speedup over the
    # materialized reference at the same operating point. Only meaningful
    # on chip (CPU rehearsal runs the pallas INTERPRETER — the sub-record
    # carries `interpret: true` there); missing in pre-kernel rounds →
    # n/a per the contract.
    ("gate.kernel.speedup", "x", "higher"),
    ("serve.p95_ms", "ms", "lower"),
    ("serve.phases.two_pool_p95_ms", "ms", "lower"),
    ("serve.mesh.imgs_per_s_per_device", "img/s/device", "higher"),
    ("serve.mesh.scaling_ratio", "x", "higher"),
    ("serve.slo.premium_p99_ratio", "x", "lower"),
    ("serve.cache.amplification", "x", "higher"),
    # ISSUE 19: how long in-flight phase-2 work sat parked across an
    # elastic dp cutover (p95 over the drill's resizes, virtual-clock
    # ms — byte-stable across hosts). Missing in pre-elastic rounds →
    # n/a per the contract; direction: lower is better.
    ("serve.elastic.cutover_pause_p95_ms", "ms", "lower"),
    ("obs.overhead_pct", "%", "lower"),
    # ISSUE 18: what sampled in-engine device profiling costs the serve
    # rehearsal — capture wall time over non-capture serve wall time as
    # the profiler accounts it. Scale-dependent (CPU-rehearsal dispatches
    # are sub-ms, so trace start/stop + parse dominates); the trend, not
    # the absolute value, is the signal. Missing in pre-prodscope rounds
    # → n/a per the contract.
    ("serve.profile.overhead_pct", "%", "lower"),
    # ISSUE 14: the cost observatory's measured step MFU (flops ÷ run_s ÷
    # platform peak) — the headline the "45% MFU" verdict becomes as a
    # number. Missing in pre-cost rounds → n/a per the benchwatch
    # contract; direction: higher is better.
    ("cost.step_mfu_pct", "%", "higher"),
    ("nullinv_s_per_image", "s/image", "lower"),
)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def load_rounds(root: str) -> List[Tuple[int, dict]]:
    """(round number, parsed headline dict) for every committed round that
    has one, ascending. Rounds whose measurement never produced a parsed
    line (r01's backend failure) are skipped — there is nothing to
    compare."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (ValueError, OSError):
            continue
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and parsed.get("metric"):
            out.append((int(m.group(1)), parsed))
    out.sort(key=lambda rp: rp[0])
    return out


def lookup(parsed: dict, dotted: str) -> Optional[float]:
    """Resolve a dotted key path to a number, None when absent/non-numeric."""
    node = parsed
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def pick_comparison(rounds: List[Tuple[int, dict]]
                    ) -> Tuple[Optional[Tuple[int, dict]],
                               Optional[Tuple[int, dict]]]:
    """(latest, predecessor): predecessor is the most recent earlier round
    with the same headline metric (like-for-like only)."""
    if not rounds:
        return None, None
    latest = rounds[-1]
    metric = latest[1].get("metric")
    for prev in reversed(rounds[:-1]):
        if prev[1].get("metric") == metric:
            return latest, prev
    return latest, None


def compare(prev: dict, latest: dict, threshold: float) -> List[dict]:
    """One row per headline key: previous/latest values, signed delta
    fraction (positive = moved in the *better* direction), and a verdict —
    ``ok`` / ``improved`` / ``REGRESSION`` / ``n/a``."""
    rows = []
    for key, unit, direction in HEADLINE_KEYS:
        a, b = lookup(prev, key), lookup(latest, key)
        row = {"key": key, "unit": unit, "direction": direction,
               "prev": a, "latest": b}
        if a is None or b is None or a == 0:
            row["delta"] = None
            row["status"] = "n/a"
        else:
            raw = (b - a) / abs(a)
            delta = raw if direction == "higher" else -raw
            row["delta"] = delta
            row["status"] = ("REGRESSION" if delta < -threshold
                             else "improved" if delta > threshold else "ok")
        rows.append(row)
    return rows


def watch(root: str, threshold: float = 0.10) -> dict:
    """The whole check as one call (the quality gate's entry point)."""
    rounds = load_rounds(root)
    latest, prev = pick_comparison(rounds)
    if latest is None:
        return {"comparable": False, "rows": [], "regressions": [],
                "note": ("no comparable round: no BENCH_r*.json rounds "
                         "with a parsed headline in the archive")}
    if prev is None:
        return {"comparable": False, "rows": [], "regressions": [],
                "latest_round": latest[0],
                "note": (f"no comparable round: r{latest[0]:02d} "
                         f"({latest[1].get('metric')}) has no earlier "
                         f"round with the same headline metric — nothing "
                         f"like-for-like to diff")}
    rows = compare(prev[1], latest[1], threshold)
    return {"comparable": True, "latest_round": latest[0],
            "prev_round": prev[0], "threshold": threshold, "rows": rows,
            "regressions": [r for r in rows if r["status"] == "REGRESSION"]}


def render(report: dict) -> str:
    if not report["comparable"]:
        return f"bench_trend: {report['note']}"
    lines = [f"bench_trend: r{report['prev_round']:02d} -> "
             f"r{report['latest_round']:02d} "
             f"(threshold {report['threshold'] * 100:.0f}%)"]
    lines.append(f"  {'key':34s} {'prev':>12s} {'latest':>12s} "
                 f"{'delta':>8s}  verdict")
    for r in report["rows"]:
        prev = "-" if r["prev"] is None else f"{r['prev']:.4g}"
        latest = "-" if r["latest"] is None else f"{r['latest']:.4g}"
        delta = ("-" if r["delta"] is None
                 else f"{r['delta'] * 100:+.1f}%")
        lines.append(f"  {r['key']:34s} {prev:>12s} {latest:>12s} "
                     f"{delta:>8s}  {r['status']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the BENCH_r*.json rounds (default: the "
             "repo root)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression budget as a fraction (default 0.10: a "
                         "headline key moving >10%% the wrong way fails)")
    args = ap.parse_args(argv)
    report = watch(args.root, args.threshold)
    print(render(report))
    if report["regressions"]:
        keys = ", ".join(r["key"] for r in report["regressions"])
        print(f"BENCH TREND REGRESSION: {keys}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
