"""Deterministic synthetic arrival-trace generator for the serve layer.

Emits the serve JSONL request format (``p2p_tpu.serve.request.Request``)
with virtual ``arrival_ms`` stamps drawn from a seeded RNG — the same seed
always produces byte-identical traces, so the bench ``serve`` rehearsal and
the tests replay exactly the load they claim to.

Two arrival processes:

- ``poisson`` — exponential interarrivals at ``--rate`` requests/second:
  the steady-traffic model the dynamic batcher's occupancy is measured on.
- ``burst``  — groups of ``--burst-size`` simultaneous arrivals separated
  by ``--burst-gap-ms`` of silence: the backpressure/queue-depth stressor.

Requests cycle through a small prompt corpus of 2-prompt replace edits
sharing one compile key (seeds and prompts vary — traced values — so the
whole trace rides one compiled program per bucket; that is the point of
compile-key bucketing). ``--distinct-keys N`` spreads the trace over N
step-counts instead, for cache-pressure experiments. ``--gate-mix`` draws
each request's phase-gate spec from a weighted distribution (e.g.
``0.5:2,off:1``) with the same seeded RNG, so a trace actually exercises
the serve layer's phase hand-off and mixed-phase packing; the default
(no mix, no ``--gate``) keeps every request ungated — byte-identical to
pre-gate-mix traces. ``--tenant-mix``/``--tier-mix`` (ISSUE 12) draw the
SLO scheduling fields (``tenant``, ``tier``) per request the same way —
each mix on its OWN derived RNG stream, so adding or dropping any mix
leaves arrivals, seeds and the other mixes byte-identical. ``--zipf S``
(ISSUE 13) draws each request's *identity* (prompt pair + seed — its
semantic-cache content) from a Zipf(S) rank distribution over
``--zipf-universe`` identities on the same separate-stream discipline, so
popular requests repeat the way real traffic does while arrivals and
deadlines stay byte-identical to the non-zipf trace. ``--diurnal``
(ISSUE 19) modulates the poisson arrival *rate* through a sinusoidal
day-curve — a deterministic multiplier on each drawn gap, so the base
RNG stream is consumed identically and switching the mode off restores
the byte-identical flat trace; the curve's phase offset rides its own
derived stream. Elastic-serving drills use it for realistic pressure
swings (peaks that justify a scale-up, troughs that justify a shrink).

    python tools/loadgen.py --n 48 --mode poisson --rate 20 --seed 0 \
        --steps 4 --out demo.jsonl

``--duration-ms`` switches to the streaming long-trace mode
(:func:`generate_stream`): requests are emitted one line at a time until
the virtual-clock horizon, never materialized — tools/soak.py drives
hours-equivalent traces through it. The RNG draws per request (gap, seed,
optional gate) in request order, so the first K requests of a stream are
byte-identical to the finite ``--n K`` trace with the same seed — the
seed-stable prefix contract pinned in tests/test_loadgen.py.

Compat note (ISSUE 9): the per-request draw order replaced the original
vectorized draws (all gaps first, then seeds), so a given (seed, n)
poisson trace has different arrivals/seeds than the same invocation
produced before the lifecycle PR. Every in-repo consumer compares
within-run (drills, parity legs, bench A/B), but committed BENCH rounds
recorded before the change ran a *different seeded workload* for their
``serve``/``resilience`` blocks than post-change rounds will — treat the
bench-trend comparison across that boundary accordingly.

Two optional schedule sections make a trace a chaos drill
(tools/chaos_drill.py):

- ``--cancel-rate`` interleaves seeded ``{"cancel": <id>}`` markers into
  the stream — each victim is cancelled one arrival after it was admitted,
  so cancellation-before-dispatch is actually exercised.
- ``--fault-rate`` emits a ``serve.chaos.FaultPlan`` JSON next to the
  trace (``--fault-plan-out``, default ``<out>.faults.json``): each
  request id draws a fault kind with the given probability from the same
  seed, so trace + plan regenerate byte-identically together.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

_CORPUS = (
    ("a squirrel eating a burger", "a squirrel eating a lasagna"),
    ("a cat riding a bike", "a dog riding a bike"),
    ("a painting of a lighthouse", "a painting of a windmill"),
    ("a bowl of apples on a table", "a bowl of oranges on a table"),
)


def _parse_mix(spec: str, what: str, convert) -> List[tuple]:
    """Shared ``value:weight,...`` mix parser: ``off``/``none`` meaning
    the field is absent, a bare entry meaning weight 1, weights positive.
    ``convert`` maps the raw value string to its typed form."""
    out: List[tuple] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            val, w_str = part.rsplit(":", 1)
            weight = float(w_str)
        else:
            val, weight = part, 1.0
        if weight <= 0:
            raise ValueError(f"{what} weight must be positive in {part!r}")
        val = val.strip()
        out.append((None if val in ("off", "none") else convert(val),
                    weight))
    if not out:
        raise ValueError(f"empty {what} {spec!r}")
    return out


def parse_gate_mix(spec: str) -> List[tuple]:
    """``"0.5:2,off:1,auto:1"`` → ``[(0.5, 2.0), (None, 1.0), ('auto',
    1.0)]`` — weighted gate specs, ``off``/``none`` meaning ungated, a
    bare entry meaning weight 1. Weights must be positive."""
    def convert(val):
        if val == "auto":
            return "auto"
        return float(val) if "." in val else int(val)

    return _parse_mix(spec, "gate mix", convert)


def parse_name_mix(spec: str, what: str = "mix") -> List[tuple]:
    """``"premium:1,best_effort:3"`` / ``"acme:2,globex:1,off:1"`` →
    weighted *string* values for the ``--tier-mix``/``--tenant-mix``
    per-request draws (``off``/``none`` = the request carries no such
    field). Same syntax and weight rules as :func:`parse_gate_mix`."""
    return _parse_mix(spec, what, str)


def parse_diurnal(spec: str) -> dict:
    """Parse the ``--diurnal`` value: ``on`` (defaults) or a comma
    ``k=v`` list over ``period_ms`` (one full day-curve cycle of virtual
    time), ``low`` and ``high`` (the rate multiplier at trough/peak).
    The defaults swing a 4 s virtual day between 0.25× and 4× the base
    rate — wide enough that an elastic mesh crosses both its scale-up
    and scale-down thresholds every cycle."""
    out = {"period_ms": 4000.0, "low": 0.25, "high": 4.0}
    s = (spec or "").strip()
    if s not in ("", "on", "default"):
        for part in s.split(","):
            if "=" not in part:
                raise ValueError(f"--diurnal expects 'on' or 'k=v,...', "
                                 f"got {spec!r}")
            k, v = part.split("=", 1)
            k = k.strip()
            if k not in out:
                raise ValueError(f"unknown --diurnal field {k!r}; valid: "
                                 f"{', '.join(sorted(out))}")
            out[k] = float(v)
    if out["period_ms"] <= 0:
        raise ValueError(f"--diurnal period_ms must be positive, "
                         f"got {out['period_ms']}")
    if not 0 < out["low"] <= out["high"]:
        raise ValueError(f"--diurnal needs 0 < low <= high, got "
                         f"low={out['low']} high={out['high']}")
    return out


def generate_stream(
    duration_ms: Optional[float] = None,
    *,
    n: Optional[int] = None,
    mode: str = "poisson",
    rate_per_s: float = 20.0,
    seed: int = 0,
    steps: int = 50,
    scheduler: str = "ddim",
    burst_size: int = 8,
    burst_gap_ms: float = 500.0,
    deadline_ms: Optional[float] = None,
    distinct_keys: int = 1,
    gate=None,
    gate_mix: Optional[List[tuple]] = None,
    tenant_mix: Optional[List[tuple]] = None,
    tier_mix: Optional[List[tuple]] = None,
    zipf_s: Optional[float] = None,
    zipf_universe: int = 32,
    diurnal: Optional[dict] = None,
):
    """Yield request dicts in arrival order until ``arrival_ms`` would
    exceed ``duration_ms`` (and/or ``n`` requests have been produced; both
    ``None`` = unbounded) — the streaming long-trace mode: a multi-hour
    virtual-clock soak trace is never materialized in memory.

    **Seed-stable prefix contract** (pinned in tests/test_loadgen.py): the
    RNG draws per request, in request order — one interarrival gap, one
    seed, then (with a mix) one gate/tenant/tier draw, each on its own
    separate derived stream — so any prefix of a stream is independent of
    the horizon: the first K requests are byte-identical for every
    ``duration_ms``/``n`` ≥ K, and :func:`generate_trace` is literally
    ``list(generate_stream(n=K))``. Every mix rides its *own* derived RNG
    stream, so adding (or dropping) one mix never perturbs arrivals,
    seeds, or another mix's draws — a tenant/tier-mixed trace is
    byte-identical to the mix-less trace everywhere but its own fields
    (the ``--gate-mix`` discipline).

    ``zipf_s`` (ISSUE 13) switches popularity on: each request's
    *identity* — its (prompt pair, seed), i.e. its semantic-cache content
    — is drawn from a Zipf(s) rank distribution over ``zipf_universe``
    distinct identities, so popular requests repeat the way real traffic
    does and the serve layer's content-addressed cache has something to
    hit. The rank draws (and the fixed identity table) ride their OWN
    derived RNG streams and the main stream's per-request seed draw still
    happens (discarded), so arrivals, deadlines and every other mix stay
    byte-identical to the non-zipf trace — the ``--gate-mix``
    discipline.

    ``diurnal`` (ISSUE 19, :func:`parse_diurnal` dict) modulates the
    poisson *rate* through a sinusoidal day-curve: each drawn gap is
    divided by a deterministic multiplier evaluated at the current
    virtual time, so the base stream's draw order and count are
    untouched — ``diurnal=None`` reproduces the flat trace byte-for-byte
    (pinned in tests/test_loadgen.py). The curve's phase offset is one
    draw on its own derived stream (the separate-stream discipline), so
    different seeds peak at different times of "day"."""
    import math

    import numpy as np

    if mode not in ("poisson", "burst"):
        raise ValueError(f"mode must be 'poisson' or 'burst', got {mode!r}")
    if rate_per_s <= 0:
        raise ValueError(f"rate_per_s must be positive, got {rate_per_s}")
    if duration_ms is not None and duration_ms < 0:
        raise ValueError(f"duration_ms must be >= 0, got {duration_ms}")
    if zipf_s is not None and zipf_s <= 0:
        raise ValueError(f"zipf s must be positive, got {zipf_s}")
    if zipf_universe < 1:
        raise ValueError(f"zipf universe must be >= 1, got {zipf_universe}")
    day_mult = None
    if diurnal is not None:
        if mode != "poisson":
            raise ValueError("diurnal modulates the poisson rate; "
                             "mode 'burst' has no rate to modulate")
        d_period = float(diurnal.get("period_ms", 4000.0))
        d_low = float(diurnal.get("low", 0.25))
        d_high = float(diurnal.get("high", 4.0))
        if d_period <= 0 or not 0 < d_low <= d_high:
            raise ValueError(f"bad diurnal spec {diurnal!r}: needs "
                             f"period_ms > 0 and 0 < low <= high")
        # One draw on the curve's own derived stream (the --gate-mix
        # discipline): the phase offset, so different seeds peak at
        # different times of "day". Everything else is a pure function
        # of virtual time — no per-request draws, so the base stream is
        # consumed identically with the mode on or off.
        d_phase = float(np.random.RandomState(seed ^ 0xD1A7A1)
                        .random_sample()) * d_period

        def day_mult(t_ms):
            x = 0.5 * (1.0 - math.cos(
                2.0 * math.pi * (t_ms + d_phase) / d_period))
            return d_low + (d_high - d_low) * x

    def _mix_drawer(mix, salt):
        # A separate derived stream per mix (the with_cancels idiom):
        # draws must not perturb the arrival/seed stream or each other.
        total_w = sum(w for _, w in mix)
        cuts = np.cumsum([w / total_w for _, w in mix])
        mix_rng = np.random.RandomState(seed ^ salt)

        def draw():
            x = mix_rng.random_sample()
            return mix[int(np.searchsorted(cuts, x, side="right"))
                       if x < cuts[-1] else len(mix) - 1][0]
        return draw

    draw_gate = (_mix_drawer(gate_mix, 0x6A7E)
                 if gate_mix is not None else None)
    draw_tenant = (_mix_drawer(tenant_mix, 0x7E2A47)
                   if tenant_mix is not None else None)
    draw_tier = (_mix_drawer(tier_mix, 0x3C11E7)
                 if tier_mix is not None else None)
    draw_rank = None
    if zipf_s is not None:
        # Identity table: a FIXED zipf_universe of draws up front on its
        # own derived stream (independent of n/duration — the prefix-
        # stability invariant), then one rank draw per request on a
        # second derived stream. p(rank r) ∝ (r+1)^-s.
        id_rng = np.random.RandomState(seed ^ 0x21BF52)
        id_seeds = [int(id_rng.randint(0, 2 ** 31 - 1))
                    for _ in range(zipf_universe)]
        w = np.array([(r + 1.0) ** (-zipf_s) for r in range(zipf_universe)])
        zcuts = np.cumsum(w / w.sum())
        zipf_rng = np.random.RandomState(seed ^ 0x21BF53)

        def draw_rank():
            x = zipf_rng.random_sample()
            return (int(np.searchsorted(zcuts, x, side="right"))
                    if x < zcuts[-1] else zipf_universe - 1)
    rng = np.random.RandomState(seed)
    at = 0.0
    i = 0
    while True:
        if n is not None and i >= n:
            return
        if mode == "poisson":
            # The gap is drawn for every request (i=0's is discarded, not
            # skipped) so per-request RNG consumption is uniform — the
            # prefix-stability invariant.
            gap = float(rng.exponential(1000.0 / rate_per_s))
            if day_mult is not None:
                # Dividing the gap by the rate multiplier at the current
                # virtual time IS the rate modulation (thinning-free, so
                # the base draw count never changes).
                gap /= day_mult(at)
            if i:
                at += gap
        else:
            at = (i // burst_size) * burst_gap_ms
        if duration_ms is not None and at > duration_ms:
            return
        src, tgt = _CORPUS[i % len(_CORPUS)]
        # The per-request seed draw ALWAYS happens (uniform RNG
        # consumption — arrivals stay byte-identical under --zipf, whose
        # rank draw then overrides the request's identity).
        seed_draw = int(rng.randint(0, 2 ** 31 - 1))
        if draw_rank is not None:
            rank = draw_rank()
            src, tgt = _CORPUS[rank % len(_CORPUS)]
            seed_draw = id_seeds[rank]
        req = {
            "request_id": f"{mode}-{seed:04d}-{i:04d}",
            "prompt": src,
            "target": tgt,
            "mode": "replace",
            "steps": steps + (i % distinct_keys if distinct_keys > 1 else 0),
            "scheduler": scheduler,
            "seed": seed_draw,
            "arrival_ms": round(float(at), 3),
        }
        req_gate = draw_gate() if draw_gate is not None else gate
        if req_gate is not None:
            req["gate"] = req_gate
        if draw_tenant is not None:
            tenant = draw_tenant()
            if tenant is not None:
                req["tenant"] = tenant
        if draw_tier is not None:
            tier = draw_tier()
            if tier is not None:
                req["tier"] = tier
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        yield req
        i += 1


def generate_trace(
    n: int,
    mode: str = "poisson",
    rate_per_s: float = 20.0,
    seed: int = 0,
    steps: int = 50,
    scheduler: str = "ddim",
    burst_size: int = 8,
    burst_gap_ms: float = 500.0,
    deadline_ms: Optional[float] = None,
    distinct_keys: int = 1,
    gate=None,
    gate_mix: Optional[List[tuple]] = None,
    tenant_mix: Optional[List[tuple]] = None,
    tier_mix: Optional[List[tuple]] = None,
    zipf_s: Optional[float] = None,
    zipf_universe: int = 32,
    diurnal: Optional[dict] = None,
) -> List[dict]:
    """Build ``n`` request dicts sorted by ``arrival_ms`` (deterministic in
    ``seed``) — the finite materialized form of :func:`generate_stream`,
    and byte-identical to its first ``n`` yields (the seed-stable prefix
    contract). ``gate_mix`` (:func:`parse_gate_mix` pairs) draws each
    request's gate from the weighted distribution — it overrides ``gate``,
    and the draws ride a separate seed-derived RNG stream, so arrivals and
    seeds stay byte-identical to the no-mix trace. ``tenant_mix`` /
    ``tier_mix`` (:func:`parse_name_mix` pairs) draw the SLO scheduling
    fields the same way, each on its own derived stream."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    return list(generate_stream(
        None, n=n, mode=mode, rate_per_s=rate_per_s, seed=seed, steps=steps,
        scheduler=scheduler, burst_size=burst_size,
        burst_gap_ms=burst_gap_ms, deadline_ms=deadline_ms,
        distinct_keys=distinct_keys, gate=gate, gate_mix=gate_mix,
        tenant_mix=tenant_mix, tier_mix=tier_mix, zipf_s=zipf_s,
        zipf_universe=zipf_universe, diurnal=diurnal))


def stream_with_cancels(stream, seed: int, rate: float):
    """Streaming form of :func:`with_cancels` — same semantics (each
    seeded victim is cancelled right after the next arrival), same derived
    RNG stream, O(1) memory."""
    import numpy as np

    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"cancel rate must be in [0, 1], got {rate}")
    rng = np.random.RandomState(seed ^ 0x5CA1AB1E)
    pending_cancel = None
    for req in stream:
        yield req
        if pending_cancel is not None:
            yield {"cancel": pending_cancel}
            pending_cancel = None
        if rng.random_sample() < rate:
            pending_cancel = req["request_id"]


def with_cancels(trace: List[dict], seed: int, rate: float) -> List[dict]:
    """Interleave seeded ``{"cancel": id}`` markers: each victim (drawn
    with probability ``rate``) is cancelled right after the *next* arrival,
    so it is in the queue but (usually) not yet dispatched. The last
    request has no later arrival to ride and is never a victim. Cancel
    markers carry no ``arrival_ms`` — the serve trace parser times them by
    stream position. (The materialized form of
    :func:`stream_with_cancels`.)"""
    return list(stream_with_cancels(iter(trace), seed, rate))


def fault_plan_dict(trace: List[dict], seed: int, rate: float,
                    kinds=("transient", "poison", "nan")) -> dict:
    """A ``serve.chaos.FaultPlan`` (as its JSON dict) drawn over the
    trace's request ids — same seed + same trace ⇒ byte-identical plan."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from p2p_tpu.serve.chaos import FaultPlan

    rids = [r["request_id"] for r in trace if "request_id" in r]
    return FaultPlan.generate(seed, rids, rate=rate,
                              kinds=tuple(kinds)).to_dict()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=48)
    ap.add_argument("--duration-ms", type=float, default=None, metavar="MS",
                    help="streaming long-trace mode: emit requests until "
                         "arrival_ms exceeds this virtual-clock horizon, "
                         "one line at a time (nothing materialized — soak "
                         "traces can be hours-equivalent). Overrides --n; "
                         "incompatible with --fault-rate, whose plan needs "
                         "the finite id list")
    ap.add_argument("--mode", choices=("poisson", "burst"), default="poisson")
    ap.add_argument("--rate", type=float, default=20.0,
                    help="poisson arrival rate, requests/second")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--scheduler", choices=("ddim", "plms", "dpm"),
                    default="ddim")
    ap.add_argument("--burst-size", type=int, default=8)
    ap.add_argument("--burst-gap-ms", type=float, default=500.0)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--distinct-keys", type=int, default=1,
                    help="spread the trace over this many step-counts "
                         "(distinct compile keys) for cache-pressure runs")
    ap.add_argument("--gate", default=None,
                    help="phase-gate spec stamped on every request "
                         "('auto', a fraction, or a step index)")
    ap.add_argument("--gate-mix", default=None, metavar="SPEC",
                    help="weighted gate distribution drawn per request "
                         "from the trace seed, e.g. '0.5:2,off:1,auto:1' "
                         "(value ':' weight; 'off'/'none' = ungated; bare "
                         "value = weight 1). Overrides --gate; exercises "
                         "the serve layer's phase hand-off and "
                         "mixed-phase packing")
    ap.add_argument("--tenant-mix", default=None, metavar="SPEC",
                    help="weighted tenant distribution drawn per request "
                         "on its own derived RNG stream, e.g. "
                         "'acme:2,globex:1,off:1' ('off'/'none' = no "
                         "tenant field; bare value = weight 1) — "
                         "arrivals/seeds stay byte-identical to the "
                         "mix-less trace (the --gate-mix discipline)")
    ap.add_argument("--tier-mix", default=None, metavar="SPEC",
                    help="weighted SLO-tier distribution drawn per "
                         "request on its own derived RNG stream, e.g. "
                         "'premium:1,best_effort:3' (tiers: premium, "
                         "standard, best_effort; 'off'/'none' = no tier "
                         "field)")
    ap.add_argument("--zipf", type=float, default=None, metavar="S",
                    help="popularity mode (ISSUE 13): draw each request's "
                         "identity — prompt pair + seed, i.e. its semantic-"
                         "cache content — from a Zipf(S) rank distribution "
                         "over --zipf-universe distinct identities, on its "
                         "own derived RNG stream (arrivals/deadlines stay "
                         "byte-identical to the non-zipf trace)")
    ap.add_argument("--zipf-universe", type=int, default=32, metavar="K",
                    help="distinct request identities under --zipf "
                         "(default 32)")
    ap.add_argument("--diurnal", default=None, nargs="?", const="on",
                    metavar="on|k=v,...",
                    help="diurnal traffic mode (ISSUE 19): modulate the "
                         "poisson rate through a sinusoidal day-curve — "
                         "'on' or a comma list over period_ms/low/high "
                         "(defaults 4000/0.25/4). Deterministic multiplier "
                         "on each drawn gap: arrivals are byte-identical "
                         "to the flat trace when the mode is off; gives "
                         "elastic-serving drills realistic pressure "
                         "swings (poisson only)")
    ap.add_argument("--cancel-rate", type=float, default=0.0,
                    help="interleave seeded {'cancel': id} markers at this "
                         "per-request probability (each victim cancelled "
                         "one arrival after admission)")
    ap.add_argument("--fault-rate", type=float, default=0.0,
                    help="emit a chaos FaultPlan JSON drawing a fault per "
                         "request id at this probability "
                         "(see --fault-plan-out)")
    ap.add_argument("--fault-kinds", default="transient,poison,nan",
                    help="comma list of fault kinds the plan draws from "
                         "(transient, poison, fatal, hang, nan)")
    ap.add_argument("--fault-plan-out", default=None,
                    help="where to write the FaultPlan JSON (default: "
                         "<--out>.faults.json; required with --fault-rate "
                         "when the trace goes to stdout)")
    ap.add_argument("--out", default=None,
                    help="write the JSONL trace here (default: stdout)")
    args = ap.parse_args(argv)

    gate = args.gate
    if isinstance(gate, str) and gate != "auto":
        gate = float(gate) if "." in gate else int(gate)
    gate_mix = parse_gate_mix(args.gate_mix) if args.gate_mix else None
    tenant_mix = (parse_name_mix(args.tenant_mix, "tenant mix")
                  if args.tenant_mix else None)
    tier_mix = (parse_name_mix(args.tier_mix, "tier mix")
                if args.tier_mix else None)
    try:
        diurnal = (parse_diurnal(args.diurnal)
                   if args.diurnal is not None else None)
    except ValueError as e:
        ap.error(str(e))
    if args.duration_ms is not None:
        if args.fault_rate > 0:
            ap.error("--fault-rate needs a finite --n trace (the fault "
                     "plan draws over the complete request-id list)")
        stream = generate_stream(
            args.duration_ms, mode=args.mode, rate_per_s=args.rate,
            seed=args.seed, steps=args.steps, scheduler=args.scheduler,
            burst_size=args.burst_size, burst_gap_ms=args.burst_gap_ms,
            deadline_ms=args.deadline_ms, distinct_keys=args.distinct_keys,
            gate=gate, gate_mix=gate_mix, tenant_mix=tenant_mix,
            tier_mix=tier_mix, zipf_s=args.zipf,
            zipf_universe=args.zipf_universe, diurnal=diurnal)
        if args.cancel_rate > 0:
            stream = stream_with_cancels(stream, args.seed,
                                         args.cancel_rate)
        out = open(args.out, "w") if args.out else sys.stdout
        try:
            for req in stream:
                out.write(json.dumps(req) + "\n")
        finally:
            if out is not sys.stdout:
                out.close()
        return 0
    trace = generate_trace(
        args.n, mode=args.mode, rate_per_s=args.rate, seed=args.seed,
        steps=args.steps, scheduler=args.scheduler,
        burst_size=args.burst_size, burst_gap_ms=args.burst_gap_ms,
        deadline_ms=args.deadline_ms, distinct_keys=args.distinct_keys,
        gate=gate, gate_mix=gate_mix, tenant_mix=tenant_mix,
        tier_mix=tier_mix, zipf_s=args.zipf,
        zipf_universe=args.zipf_universe, diurnal=diurnal)
    if args.fault_rate > 0:
        plan_path = args.fault_plan_out or (
            args.out and args.out + ".faults.json")
        if not plan_path:
            ap.error("--fault-rate needs --fault-plan-out (or --out)")
        plan = fault_plan_dict(trace, args.seed, args.fault_rate,
                               kinds=[k for k in
                                      args.fault_kinds.split(",") if k])
        with open(plan_path, "w") as f:
            json.dump(plan, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {plan_path} "
              f"({len(plan['by_request'])} faulted ids)", file=sys.stderr)
    if args.cancel_rate > 0:
        trace = with_cancels(trace, args.seed, args.cancel_rate)
    out = open(args.out, "w") if args.out else sys.stdout
    try:
        for req in trace:
            out.write(json.dumps(req) + "\n")
    finally:
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
